"""S3-compatible HTTP API server (reference cmd/api-router.go:82 +
cmd/object-handlers.go / cmd/bucket-handlers.go): path-style routing over an
ObjectLayer, SigV4 auth, XML responses.

Threaded stdlib HTTP server: request concurrency maps to the dispatch
queue's batching (many in-flight PUT/GET blocks coalesce into single device
launches); the reference's per-node request throttle (cmd/handler-api.go:29)
is the QoS admission controller (minio_tpu.qos.admission): per-class token
buckets + a bounded-wait concurrency gate answering 503 SlowDown +
Retry-After under overload."""
from __future__ import annotations

import hashlib
import os
import socket
import threading
import urllib.parse
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..bucket import BucketMetadataSys
from ..objectlayer import ObjectLayer, ObjectOptions
from ..objectlayer import datatypes as dt
from ..utils.hashreader import (BadDigestError, HashReader,
                                SHA256MismatchError)
from . import xmlutil as xu
from .auth import (STREAMING_PAYLOAD, UNSIGNED_PAYLOAD, AuthError,
                   ChunkedSigV4Reader, SigV4Verifier, parse_auth_header,
                   signing_key)

MAX_OBJECT_SIZE = 5 << 40       # 5 TiB (docs/minio-limits.md:25)
MAX_PUT_SIZE = 5 << 30          # single PUT cap 5 GiB

_HOST_ID = ""


def host_id() -> str:
    """Stable per-host opaque id stamped as ``x-amz-id-2`` / error-XML
    ``HostId`` (the reference derives its extended request id the same
    way: an opaque token identifying the serving host)."""
    global _HOST_ID
    if not _HOST_ID:
        import base64
        _HOST_ID = base64.b64encode(hashlib.sha256(
            socket.gethostname().encode()).digest()).decode()[:44]
    return _HOST_ID


class S3Server:
    """Owns the ObjectLayer, auth, bucket metadata; builds the HTTP server."""

    def __init__(self, objlayer: ObjectLayer, address: str = "0.0.0.0",
                 port: int = 9000, region: str = "us-east-1",
                 access_key: str = "", secret_key: str = "",
                 max_requests: int = 256,
                 extra_addresses: list[tuple[str, int]] | None = None):
        #: additional (host, port) bindings served alongside the main
        #: one (reference multi-addr xhttp.Listener)
        self.extra_addresses = list(extra_addresses or [])
        self._extra_httpds: list[ThreadingHTTPServer] = []
        self.obj = objlayer
        self.region = region
        self.access_key = access_key or os.environ.get(
            "MINIO_ROOT_USER", "minioadmin")
        self.secret_key = secret_key or os.environ.get(
            "MINIO_ROOT_PASSWORD", "minioadmin")
        self.bucket_meta = BucketMetadataSys(objlayer)
        #: pluggable credential lookup — IAM replaces this (minio_tpu.iam)
        self.lookup_secret = lambda ak: (
            self.secret_key if ak == self.access_key else None)
        #: optional IAM policy gate: fn(access_key, action, bucket, object)
        self.authorize = None
        self.iam = None
        #: optional event notifier: fn(event_name, bucket, object_info)
        self.notify = None
        self._notifier = None
        #: federation bucket DNS (dist.federation.BucketDNS) — None when
        #: the deployment is not federated
        self.federation = None
        self._notifier_lock = threading.Lock()
        self.verifier = SigV4Verifier(lambda ak: self.lookup_secret(ak),
                                      region)
        self.address = address
        self.port = port
        from ..crypto import kms as _kms_mod
        _kms_mod.configure(self.secret_key)
        cfg = None
        if objlayer is not None:
            # attach the config KVS to its persistence backend so stored
            # settings survive restarts (env > stored > default)
            from ..config import get_config_sys
            cfg = get_config_sys(objlayer)
        # QoS admission control (minio_tpu.qos.admission) replaces the
        # old bare 256-permit semaphore: a request that cannot get a slot
        # within the bounded wait (or whose class token bucket is empty)
        # is answered 503 SlowDown + Retry-After instead of parking a
        # handler thread
        from ..qos import AdmissionController
        if cfg is not None and cfg.source("api", "requests_max") != \
                "default":
            # operator-set env/stored value wins over the constructor
            # default; an explicit constructor argument wins otherwise
            max_requests = cfg.get_int("api", "requests_max", max_requests)
        self.qos_admission = AdmissionController(max_requests=max_requests)
        if cfg is not None:
            import weakref
            ref = weakref.ref(self)

            def _apply_api(c, _ref=ref):
                s = _ref()
                if s is not None and \
                        c.source("api", "requests_max") != "default":
                    s.qos_admission.reconfigure(
                        c.get_int("api", "requests_max",
                                  s.qos_admission.max_requests))

            cfg.on_apply("api", _apply_api)
            # declarative KVS fault rules (chaos harness): applied once
            # at start and on every dynamic `fault` subsystem change
            from .. import fault as _fault
            cfg.on_apply("fault", _fault.apply_config)
            _fault.apply_config(cfg)
        # always-on continuous profiler (obs/profiler.py): one
        # process-global daemon whatever the server count — repeated
        # server cycles must not accumulate threads (test_leaks)
        from ..obs import profiler as _profiler
        _profiler.ensure_started()
        self._httpd: ThreadingHTTPServer | None = None
        #: internal RPC services mounted under /minio/<name>/v1/<method>
        #: (storage/lock/peer — populated by dist.node.Node)
        self.internal: dict[str, object] = {}
        #: live accepted connections — node-kill chaos severs these the
        #: way a dead process would (keep-alive peers must not keep
        #: talking to a "killed" node through zombie sockets)
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def _track_conn(self, sock) -> None:
        with self._conns_lock:
            self._conns.add(sock)

    def _untrack_conn(self, sock) -> None:
        with self._conns_lock:
            self._conns.discard(sock)

    def hard_close_connections(self) -> None:
        """Sever every accepted connection (fault.node.node_kill): a
        SIGKILL'd process takes its established sockets with it, so
        the in-process kill must too — otherwise peers keep completing
        RPCs against the 'dead' node over keep-alive connections."""
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def enable_iam(self):
        """Attach the IAM subsystem: per-user credentials, policy
        enforcement, STS, anonymous bucket-policy access."""
        from ..iam import IAMSys
        self.iam = IAMSys(self.obj, self.access_key, self.secret_key)
        self.lookup_secret = self.iam.lookup_secret
        self.authorize = self._iam_authorize
        return self.iam

    def create_bucket(self, bucket: str, object_lock: bool = False):
        """Bucket creation shared by the S3 and console paths: federation
        namespace check + metadata record + DNS registration, with a
        symmetric rollback when registration fails."""
        dns = self.federation
        if dns is not None:
            owners = dns.lookup(bucket)
            if owners and not dns.is_mine(owners):
                raise dt.BucketExists(bucket)
        self.obj.make_bucket(bucket)
        from ..bucket.metadata import BucketMetadata
        meta = BucketMetadata(name=bucket)
        if object_lock:
            meta.object_lock_enabled = True
            meta.versioning_enabled = True
        self.bucket_meta.set(bucket, meta)
        if dns is not None:
            from ..dist.federation import FederationConflict
            try:
                dns.put(bucket)
            except Exception as e:  # noqa: BLE001 — unregistered bucket
                # would be invisible to the federation: undo everything
                self.obj.delete_bucket(bucket, force=True)
                self.bucket_meta.remove(bucket)
                if self._notifier is not None:
                    self._notifier.invalidate(bucket)
                if isinstance(e, FederationConflict):
                    # lost the atomic claim race to another cluster
                    raise dt.BucketExists(bucket) from None
                raise dt.InvalidRequest(
                    bucket, "", f"federation DNS: {e}") from None

    def remove_bucket(self, bucket: str, force: bool = False):
        """Bucket deletion shared by the S3 and console paths."""
        if force and self.bucket_meta.get(bucket).object_lock_enabled:
            # force delete would bypass WORM retention (the reference
            # refuses force-delete on lock buckets the same way)
            raise dt.InvalidRequest(
                bucket, "",
                "force delete not allowed on object-lock buckets")
        # the bucket must exist locally before DNS is touched: deleting
        # a bucket we don't hold must not strip (or, via the restore
        # below, resurrect) another cluster's registration
        self.obj.get_bucket_info(bucket)
        if self.federation is not None:
            # unregister FIRST and fail the request when etcd is down:
            # entries take no lease, so a silently-skipped delete would
            # poison the name federation-wide forever (the reference
            # DeleteBucketHandler errors out the same way)
            try:
                self.federation.delete(bucket)
            except Exception as e:  # noqa: BLE001
                raise dt.InvalidRequest(
                    bucket, "", f"federation DNS: {e}") from None
        try:
            self.obj.delete_bucket(bucket, force=force)
        except dt.BucketNotFound:
            raise  # lost a delete race: nothing to restore
        except BaseException:
            if self.federation is not None:
                try:  # local delete failed: restore the DNS record
                    self.federation.put(bucket)
                except Exception:  # noqa: BLE001 — best effort
                    pass
            raise
        self.bucket_meta.remove(bucket)
        if self._notifier is not None:
            # a recreated bucket must not inherit the old routing rules
            self._notifier.invalidate(bucket)

    def enable_federation(self, dns):
        """Attach a federation BucketDNS (dist.federation): bucket
        create/delete register in etcd, foreign-bucket requests proxy to
        the owning cluster, ListBuckets shows the federated namespace."""
        self.federation = dns
        return dns

    def ensure_notifier(self):
        """The event notifier, created lazily when a live listener needs
        it before any target configuration. Chains with (never replaces)
        an existing notify hook — a replication chain attached earlier
        must keep firing — and the lock closes the concurrent-first-
        listener race that would orphan one notifier."""
        with self._notifier_lock:
            if self._notifier is None:
                from ..event import EventNotifier
                n = EventNotifier(self.bucket_meta, [], "", self.region)
                prev = self.notify
                if prev is None:
                    self.notify = n
                else:
                    def chained(event, bucket, oi, *a):
                        n(event, bucket, oi, *a)
                        prev(event, bucket, oi, *a)
                    self.notify = chained
                self._notifier = n
            return self._notifier

    def enable_replication(self, pool):
        """Attach a ReplicationPool: object events feed it (chained with
        any existing notifier) and GETs of locally-missing objects proxy
        to the bucket's target (reference proxy-to-target on GET miss)."""
        self.replication = pool
        # read-chain-store of self.notify must be atomic: an unguarded
        # enable racing another notifier attach drops one of the links
        with self._notifier_lock:
            prev = self.notify

            def chained(event, bucket, oi, *a):
                pool.on_event(event, bucket, oi)
                if prev is not None:
                    prev(event, bucket, oi, *a)

            self.notify = chained
        return pool

    def enable_cross_replication(self, rs):
        """Attach the cross-node ReplicationSys (bucket/replicate.py):
        completed writes/deletes charge replication debt through the
        notify chain, and the scanner re-charges PENDING/FAILED
        leftovers each cycle. Distinct from ``enable_replication``
        (the S3-target pool): this plane ships over the dist peer RPC
        with MRF-style journalled retry."""
        self.replication_sys = rs
        # same atomic read-chain-store discipline as enable_replication
        with self._notifier_lock:
            prev = self.notify

            def chained(event, bucket, oi, *a):
                rs.charge(event, bucket, oi)
                if prev is not None:
                    prev(event, bucket, oi, *a)

            self.notify = chained
        sc = getattr(self, "scanner", None)
        if sc is not None:
            sc.replication = rs
        # replication lag rides the SLO plane as a real objective
        from ..obs import slo as _slo
        _slo.register_async_probe("replication", rs.lag_report)
        return rs

    def enable_events(self, targets: list | None = None,
                      queue_root: str = ""):
        """Attach the event-notification subsystem: persistent per-target
        delivery queues + ARN routing from bucket notification configs.
        Targets default to the env-configured webhooks
        (MINIO_TPU_NOTIFY_WEBHOOK_ENDPOINT_<ID>); the queue root defaults
        to MINIO_TPU_NOTIFY_QUEUE_DIR or .events under the cwd."""
        from ..event import EventNotifier, targets_from_env
        from ..event.notifier import targets_from_config
        if targets is None:
            targets = targets_from_env(self.region)
            try:
                from ..config import get_config_sys
                targets += targets_from_config(get_config_sys(self.obj),
                                               self.region)
            except Exception:  # noqa: BLE001 — no config plane wired
                pass
        if not queue_root:
            queue_root = os.environ.get(
                "MINIO_TPU_NOTIFY_QUEUE_DIR",
                os.path.join(os.getcwd(), ".minio-tpu-events"))
        with self._notifier_lock:
            if self._notifier is not None:
                # a lazily created (listener-only) notifier already
                # exists and live streams hold subscriptions on it —
                # attach the targets to THAT instance instead of
                # replacing it (which would orphan every open listen
                # stream and drop any chained notify hook)
                self._notifier.add_targets(targets, queue_root)
                return self._notifier
            self._notifier = EventNotifier(self.bucket_meta, targets,
                                           queue_root, self.region)
            prev = self.notify
            if prev is None:
                self.notify = self._notifier
            else:
                n = self._notifier

                def chained(event, bucket, oi, *a):
                    n(event, bucket, oi, *a)
                    prev(event, bucket, oi, *a)

                self.notify = chained
            return self._notifier

    def _iam_authorize(self, access_key: str, action: str, bucket: str,
                       object: str) -> bool:
        if self.iam.is_allowed(access_key, action, bucket, object):
            return True
        # bucket policy may grant the (possibly anonymous) principal
        if bucket:
            from ..iam.policy import Policy, policy_allows
            meta = self.bucket_meta.get(bucket)
            if meta.policy_json:
                try:
                    bp = Policy.parse(meta.policy_json)
                except ValueError:
                    return False
                resource = f"{bucket}/{object}" if object else bucket
                return policy_allows([bp], action, resource,
                                     principal=access_key or "*")
        return False

    # --- server lifecycle ---------------------------------------------------

    def build(self) -> ThreadingHTTPServer:
        server = self

        class Handler(_S3Handler):
            s3 = server

        class TunedServer(ThreadingHTTPServer):
            """Listener tuning (reference cmd/http/server.go +
            listener.go): deep accept backlog for bursty S3 clients,
            TCP_NODELAY + keepalive on every accepted connection so small
            metadata responses don't sit in Nagle buffers and dead peers
            get reaped, and an idle read timeout so keep-alive
            connections that go quiet release their handler thread
            (thread-per-connection's slowloris exposure; reference
            ReadTimeout, cmd/http/server.go)."""
            request_queue_size = 1024
            daemon_threads = True
            idle_timeout_s = float(os.environ.get(
                "MINIO_TPU_HTTP_IDLE_TIMEOUT_S", "120"))

            def process_request(self, request, client_address):
                try:
                    request.setsockopt(socket.IPPROTO_TCP,
                                       socket.TCP_NODELAY, 1)
                    request.setsockopt(socket.SOL_SOCKET,
                                       socket.SO_KEEPALIVE, 1)
                    if self.idle_timeout_s > 0:
                        request.settimeout(self.idle_timeout_s)
                except OSError:
                    pass
                server._track_conn(request)
                super().process_request(request, client_address)

            def shutdown_request(self, request):
                server._untrack_conn(request)
                super().shutdown_request(request)

            def handle_error(self, request, client_address):
                # a client (or node-kill chaos) severing the socket
                # mid-response is normal churn, not a server error —
                # everything else keeps the stderr traceback
                import sys as _sys
                et = _sys.exc_info()[0]
                if et is not None and issubclass(
                        et, (BrokenPipeError, ConnectionResetError,
                             TimeoutError, socket.timeout)):
                    return
                super().handle_error(request, client_address)

        httpd = TunedServer((self.address, self.port), Handler)
        self._httpd = httpd
        self.port = httpd.server_address[1]
        # multi-address listening (reference xhttp.Listener,
        # cmd/http/listener.go: one logical server accepting on several
        # host:port bindings): each extra address gets its own accept
        # loop feeding the same handler/server state
        try:
            for host, port in self.extra_addresses:
                extra = TunedServer((host, port), Handler)
                self._extra_httpds.append(extra)
        except OSError:
            # a failed extra bind must not leak the sockets already
            # bound (or leave a shutdown() that would wait forever on
            # servers whose serve_forever never ran)
            for s in self._extra_httpds:
                s.server_close()
            self._extra_httpds = []
            httpd.server_close()
            self._httpd = None
            raise
        self.extra_ports = [s.server_address[1]
                            for s in self._extra_httpds]
        return httpd

    def serve_forever(self):
        httpd = self.build()
        for extra in self._extra_httpds:
            threading.Thread(target=extra.serve_forever,
                             name="minio-tpu-http-extra",
                             daemon=True).start()
        httpd.serve_forever()

    def start_background(self) -> threading.Thread:
        httpd = self.build()
        t = threading.Thread(target=httpd.serve_forever,
                             name="minio-tpu-http", daemon=True)
        t.start()
        for extra in self._extra_httpds:
            threading.Thread(target=extra.serve_forever,
                             name="minio-tpu-http-extra",
                             daemon=True).start()
        return t

    def start_background_services(self, scan_interval_s: float = 300.0):
        """Attach and start the background plane (reference
        cmd/server-main.go:508-514 initAutoHeal / initDataScanner + MRF):
        MRF healer, data scanner with lifecycle+transition hooks, fresh-
        disk auto-heal monitor. Idempotent; services land on self.mrf /
        self.scanner / self.autoheal, where the admin bg-heal-status op,
        peer RPC and the heal metrics group already look for them."""
        if getattr(self, "mrf", None) is not None:
            return
        from ..bucket.lifecycle import LifecycleSys
        from ..obs.metrics import _all_disks
        from ..scanner.autoheal import AutoHealMonitor
        from ..scanner.mrf import MRFHealer
        from ..scanner.scanner import DataScanner
        self.mrf = MRFHealer(self.obj)
        # persist the heal queue beside the tracker state on the first
        # local disk: heal debt recorded before a crash is re-enqueued
        # at the next start instead of waiting for a deep scanner cycle
        try:
            from ..storage.xlstorage import META_BUCKET
            disk = next(d for d in _all_disks(self.obj)
                        if getattr(d, "base", ""))
            self.mrf.attach_persistence(
                os.path.join(disk.base, META_BUCKET, "mrf.json"))
        except StopIteration:
            pass
        self.mrf.start()
        lc = LifecycleSys(self.obj, self.bucket_meta, self.transition)
        self.scanner = DataScanner(
            self.obj, interval_s=float(os.environ.get(
                "MINIO_TPU_SCANNER_INTERVAL_S", str(scan_interval_s))),
            mrf=self.mrf, lifecycle=lc).start()
        self.autoheal = AutoHealMonitor(
            self.obj, _all_disks(self.obj)).start()

        # wire the degraded-path signals into the background plane:
        # partial/bitrot detections enqueue MRF heals, and a health-
        # tracked disk that re-onlines kicks the auto-heal monitor so
        # the objects it missed get rebuilt promptly
        def _disk_state(disk, state, _srv=self):
            if state == "ok" and getattr(_srv, "autoheal", None) is not None:
                from ..scanner.autoheal import set_healing_tracker
                try:
                    set_healing_tracker(disk)
                except Exception:  # noqa: BLE001 — disk may still be sick
                    pass
                _srv.autoheal.kick()
        for layer in self._erasure_layers():
            layer.on_partial = self.mrf.add_partial
            layer.on_disk_state = _disk_state

    def _erasure_layers(self) -> list:
        """Every ErasureObjects under any ObjectLayer shape (one set, a
        sets layer, or server pools)."""
        obj = self.obj
        if hasattr(obj, "pools"):
            out = []
            for p in obj.pools:
                out.extend(p.sets if hasattr(p, "sets") else [p])
            return out
        if hasattr(obj, "sets"):
            return list(obj.sets)
        return [obj] if hasattr(obj, "on_partial") else []

    def shutdown(self):
        for svc_name in ("scanner", "autoheal", "mrf", "replication_sys"):
            svc = getattr(self, svc_name, None)
            if svc is not None:
                try:
                    svc.stop()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        if self._httpd is not None:
            self._httpd.shutdown()
        for extra in self._extra_httpds:
            extra.shutdown()

    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def tiers(self):
        """Lazy tier registry (reference globalTierConfigMgr)."""
        if getattr(self, "_tiers", None) is None:
            from ..bucket.tiers import TierRegistry
            self._tiers = TierRegistry(self.obj)
        return self._tiers

    @property
    def transition(self):
        if getattr(self, "_transition", None) is None:
            from ..bucket.transition import TransitionSys
            self._transition = TransitionSys(self.obj, self.tiers,
                                             self.bucket_meta)
        return self._transition


class _ChunkedWriter:
    """HTTP/1.1 chunked transfer encoding over a raw socket file — lets
    event-stream responses (S3 Select) stream frames without knowing the
    total length up front."""

    def __init__(self, wfile):
        self.wfile = wfile

    def write(self, b: bytes) -> int:
        if b:
            self.wfile.write(f"{len(b):x}\r\n".encode() + b + b"\r\n")
        return len(b)

    def flush(self):  # writer-protocol consumers (zipfile) call this
        pass

    def close(self):
        self.wfile.write(b"0\r\n\r\n")


class _CountingWriter:
    """Transparent wfile proxy counting bytes written — the per-bucket
    traffic counters (obs/bucketstats) read ``sent`` deltas per request
    on a keep-alive connection, so streamed GET bodies are charged
    without any hook inside the streaming loops."""

    __slots__ = ("_w", "sent")

    def __init__(self, w):
        self._w = w
        self.sent = 0

    def write(self, b) -> int:
        n = self._w.write(b)
        self.sent += len(b)
        return n

    def __getattr__(self, name):
        return getattr(self._w, name)


class _S3Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    s3: S3Server = None  # set by subclass factory

    def setup(self):
        super().setup()
        self.wfile = _CountingWriter(self.wfile)

    # silence default request logging (trace subsystem handles this)
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    # --- plumbing -----------------------------------------------------------

    def _parse(self):
        split = urllib.parse.urlsplit(self.path)
        self.raw_query = split.query
        self.url_path = urllib.parse.unquote(split.path)
        self.query = urllib.parse.parse_qs(split.query,
                                           keep_blank_values=True)
        parts = self.url_path.lstrip("/").split("/", 1)
        self.bucket = parts[0]
        self.key = parts[1] if len(parts) > 1 else ""
        self.hdr = {k.lower(): v for k, v in self.headers.items()}
        self._consumed = 0  # request-body bytes read (keep-alive hygiene)

    def q(self, key: str, default: str = "") -> str:
        v = self.query.get(key)
        return v[0] if v else default

    def has_q(self, key: str) -> bool:
        return key in self.query

    def _api_name(self) -> str:
        """S3 API name for the per-API metric labels (the reference tags
        minio_s3_requests_total / minio_s3_ttfb_seconds_distribution with
        api="getobject"-style names, cmd/metrics-v2.go:147-154)."""
        m, b, k = self.command, self.bucket, self.key
        if not b:
            return "listbuckets" if m == "GET" else "sts"
        if k:
            if m == "GET":
                if self.has_q("uploadId"):
                    return "listobjectparts"
                for sub in ("tagging", "retention", "legal-hold", "acl"):
                    if self.has_q(sub):
                        return f"getobject{sub.replace('-', '')}"
                return "getobject"
            if m == "HEAD":
                return "headobject"
            if m == "PUT":
                if self.has_q("partNumber"):
                    return "putobjectpart"
                if "x-amz-copy-source" in self.hdr:
                    return "copyobject"
                for sub in ("tagging", "retention", "legal-hold", "acl"):
                    if self.has_q(sub):
                        return f"putobject{sub.replace('-', '')}"
                return "putobject"
            if m == "POST":
                if self.has_q("uploads"):
                    return "newmultipartupload"
                if self.has_q("uploadId"):
                    return "completemultipartupload"
                if self.has_q("select") or self.q("select-type"):
                    return "selectobjectcontent"
                if self.has_q("restore"):
                    return "restoreobject"
                return "postobject"
            if m == "DELETE":
                if self.has_q("uploadId"):
                    return "abortmultipartupload"
                if self.has_q("tagging"):
                    return "deleteobjecttagging"
                return "deleteobject"
            return m.lower()
        # bucket-level
        subs = ("policy", "lifecycle", "versioning", "notification",
                "tagging", "object-lock", "replication", "encryption",
                "quota", "versions", "uploads", "location")
        sub = next((s for s in subs if self.has_q(s)), "")
        if m == "GET":
            if sub == "versions":
                return "listobjectversions"
            if sub == "uploads":
                return "listmultipartuploads"
            if sub:
                return f"getbucket{sub.replace('-', '')}"
            return "listobjectsv2" if self.q("list-type") == "2" \
                else "listobjectsv1"
        if m == "HEAD":
            return "headbucket"
        if m == "PUT":
            return f"putbucket{sub.replace('-', '')}" if sub \
                else "putbucket"
        if m == "DELETE":
            return f"deletebucket{sub.replace('-', '')}" if sub \
                else "deletebucket"
        if m == "POST":
            if self.has_q("delete"):
                return "deletemultipleobjects"
            return "postpolicybucket"
        return m.lower()

    def _send(self, status: int, body: bytes = b"",
              content_type: str = "application/xml",
              headers: dict | None = None):
        if getattr(self, "_last_status", 0):
            # a response already started for this request — this is an
            # error surfacing MID-BODY (e.g. the object was deleted under
            # a streaming GET). Appending an error document would corrupt
            # the keep-alive framing: the client would block inside the
            # truncated body instead of seeing EOF. Cut the connection.
            self.close_connection = True
            return
        self.send_response(status)
        for k, v in (headers or {}).items():
            if v is not None and v != "":
                self.send_header(k, v)
        if body or status not in (204, 304):
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
        else:
            self.send_header("Content-Length", "0")
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def _error(self, code: str, message: str, status: int):
        if status in (204, 304):  # bodiless statuses per RFC 9110
            return self._send(status)
        self._send(status, xu.error_xml(
            code, message, getattr(self, "url_path", self.path),
            request_id=getattr(self, "_request_id", ""),
            host_id=host_id()))

    def _api_error(self, e: dt.ObjectAPIError):
        self._error(e.code, str(e), e.http_status)

    def _read_body(self) -> bytes:
        n = int(self.hdr.get("content-length", "0") or "0")
        data = self.rfile.read(n) if n else b""
        self._consumed += len(data)
        return data

    def _drain_body(self):
        """Discard any unread request body so the next request on this
        keep-alive connection parses cleanly; large remainders close the
        connection instead of burning bandwidth."""
        try:
            n = int(self.hdr.get("content-length", "0") or "0")
        except (AttributeError, ValueError):
            return
        remaining = n - getattr(self, "_consumed", 0)
        if remaining <= 0:
            return
        if remaining > (1 << 20):
            self.close_connection = True
            return
        while remaining > 0:
            chunk = self.rfile.read(min(65536, remaining))
            if not chunk:
                break
            remaining -= len(chunk)

    # --- auth ---------------------------------------------------------------

    def _authenticate(self) -> str:
        headers = dict(self.hdr)
        headers.setdefault("host", self.headers.get("Host", ""))
        return self.s3.verifier.verify(
            self.command, self.url_path, self.query, headers)

    def _authorize(self, access_key: str, action: str,
                   bucket: str | None = None, key: str | None = None):
        gate = self.s3.authorize
        if gate is None:
            if access_key == "":
                raise AuthError("AccessDenied", "anonymous access denied")
            return
        bucket = self.bucket if bucket is None else bucket
        key = self.key if key is None else key
        if not gate(access_key, action, bucket, key):
            raise AuthError("AccessDenied", f"not allowed to {action}")

    def _sts(self, body: bytes):
        """STS: AssumeRole (signed caller), AssumeRoleWithWebIdentity /
        AssumeRoleWithClientGrants (OIDC JWT against the configured
        provider) and AssumeRoleWithLDAPIdentity (simple bind) —
        reference cmd/sts-handlers.go:43-93."""
        form = dict(urllib.parse.parse_qsl(body.decode("utf-8", "replace")))
        action = form.get("Action", "AssumeRole")
        try:
            duration = int(form.get("DurationSeconds", "3600") or "3600")
        except ValueError:
            return self._error("InvalidParameterValue",
                               "DurationSeconds must be an integer", 400)
        session_policy = form.get("Policy", "").encode()
        try:
            if action == "AssumeRoleWithWebIdentity":
                cred = self.s3.iam.assume_role_with_web_identity(
                    form.get("WebIdentityToken", ""), duration,
                    session_policy)
            elif action == "AssumeRoleWithClientGrants":
                cred = self.s3.iam.assume_role_with_client_grants(
                    form.get("Token", ""), duration, session_policy)
            elif action == "AssumeRoleWithLDAPIdentity":
                cred = self.s3.iam.assume_role_with_ldap_identity(
                    form.get("LDAPUsername", ""),
                    form.get("LDAPPassword", ""), duration,
                    session_policy)
            elif action == "AssumeRole":
                try:
                    ak = self._authenticate()
                except AuthError as e:
                    return self._error(e.code, e.message, e.status)
                cred = self.s3.iam.assume_role(ak, duration,
                                               session_policy)
            else:
                return self._error("InvalidAction",
                                   f"unsupported STS action {action}",
                                   400)
        except ValueError as e:
            return self._error("InvalidParameterValue", str(e), 400)
        import datetime
        exp = datetime.datetime.fromtimestamp(
            cred.expiration, tz=datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%SZ")
        result = f"{action}Result"
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<{action}Response xmlns='
            '"https://sts.amazonaws.com/doc/2011-06-15/">'
            f"<{result}><Credentials>"
            f"<AccessKeyId>{cred.access_key}</AccessKeyId>"
            f"<SecretAccessKey>{cred.secret_key}</SecretAccessKey>"
            f"<SessionToken>minio-tpu-session</SessionToken>"
            f"<Expiration>{exp}</Expiration>"
            f"</Credentials></{result}></{action}Response>"
        ).encode()
        self._send(200, xml)

    def _body_stream(self, size: int):
        """Request-body reader honoring aws-chunked streaming signatures."""
        sha = self.hdr.get("x-amz-content-sha256", "")
        if sha == STREAMING_PAYLOAD:
            auth = parse_auth_header(self.hdr.get("authorization", ""))
            secret = self.s3.lookup_secret(auth.access_key)
            key = signing_key(secret, auth.scope_date, auth.region,
                              auth.service)
            scope = (f"{auth.scope_date}/{auth.region}/{auth.service}/"
                     "aws4_request")
            # chunked framing makes residual length unknowable: if the
            # handler errors mid-stream, close rather than drain
            self._consumed = 1 << 62
            self.close_connection = True
            return ChunkedSigV4Reader(
                self.rfile, auth.signature, key,
                self.hdr.get("x-amz-date", ""), scope)
        return _CappedReader(self.rfile, size, self)

    # --- routing ------------------------------------------------------------

    def _route(self):
        self._parse()
        # unauthenticated health endpoints (cmd/healthcheck-handler.go):
        # liveness = this process serves HTTP (the RPC reconnect pings
        # probe it DURING cluster bootstrap, when no node has an object
        # layer yet — gating it on readiness deadlocks a fresh cluster);
        # readiness/cluster = storage is actually online
        if self.url_path.startswith("/minio/health/"):
            if self.url_path.rstrip("/").endswith("/live"):
                return self._send(200, b"", "text/plain; charset=utf-8")
            ok = self.s3.obj is not None and self.s3.obj.is_ready()
            return self._send(200 if ok else 503, b"",
                              "text/plain; charset=utf-8")
        # internal RPC services (storage/lock/peer — reference
        # registerDistErasureRouters, cmd/routers.go:26-39)
        if self.url_path.startswith("/minio/") and self.s3.internal:
            parts = self.url_path.split("/", 4)
            if len(parts) >= 5 and parts[2] in self.s3.internal:
                return self._internal_rpc(parts[2], parts[4])
        if self.s3.obj is None:
            return self._error("ServerNotInitialized",
                               "server still starting", 503)
        if self.url_path.startswith("/minio/metrics") or \
                self.url_path.startswith("/minio/v2/metrics"):
            from ..obs.metrics import render_prometheus
            scope = "node" if self.url_path.rstrip("/").endswith("/node") \
                else "cluster"
            # ?attribution=1 appends the standing per-op stage
            # breakdown families (minio_tpu_stage_*, ISSUE 9)
            attribution = self.query.get("attribution", [""])[0] == "1"
            # exemplars are OpenMetrics-only syntax: emit them (and the
            # matching content type + # EOF) only on EXPLICIT
            # ?openmetrics=1 request. Not Accept-negotiated on purpose:
            # modern Prometheus lists openmetrics-text in its default
            # Accept, and this exposition keeps classic counter naming
            # ('X_total' declared as-is), which a STRICT OM parser
            # rejects wholesale — sniffing Accept would break scrapers
            # that parse the classic form fine today. A classic parser
            # conversely reads a trailing exemplar '#' as an invalid
            # timestamp, so the default form strips them.
            om = self.query.get("openmetrics", [""])[0] == "1"
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8") if om else \
                "text/plain; version=0.0.4"
            return self._send(200, render_prometheus(
                self.s3, scope, attribution=attribution,
                openmetrics=om), ctype)
        if self.url_path.startswith("/minio/admin/"):
            from .admin import handle_admin
            return handle_admin(self)
        # web console plane (reference cmd/web-router.go: /minio/webrpc
        # JSON-RPC + JWT-authenticated upload/download routes + the static
        # single-file SPA at /minio/)
        if self.url_path in ("/minio", "/minio/", "/minio/index.html"):
            from .webrpc import handle_console
            return handle_console(self)
        if self.url_path == "/minio/webrpc":
            from .webrpc import handle_webrpc
            return handle_webrpc(self)
        if self.url_path.startswith("/minio/upload/"):
            from .webrpc import handle_upload
            rest = self.url_path[len("/minio/upload/"):]
            bucket, _, obj = rest.partition("/")
            return handle_upload(self, bucket, obj)
        if self.url_path.startswith("/minio/download/"):
            from .webrpc import handle_download
            rest = self.url_path[len("/minio/download/"):]
            bucket, _, obj = rest.partition("/")
            return handle_download(self, bucket, obj)
        if self.url_path == "/minio/zip":
            from .webrpc import handle_download_zip
            return handle_download_zip(self)
        # STS endpoint: POST / with form-encoded Action (cmd/sts-handlers.go)
        # — AssumeRoleWithWebIdentity carries no Authorization header (the
        # JWT is the credential), so the gate is the Action itself
        if self.command == "POST" and self.url_path == "/" and \
                self.s3.iam is not None:
            body = self._read_body()
            if b"Action=Assume" in body or b"Action=assume" in body:
                return self._sts(body)
        # browser POST uploads authenticate via the signed policy inside
        # the form, not an Authorization header
        if self.command == "POST" and self.key == "" and \
                self.bucket and self.hdr.get("content-type", "").startswith(
                    "multipart/form-data"):
            try:
                return self.post_policy_upload()
            except dt.ObjectAPIError as e:
                return self._api_error(e)
            except AuthError as e:
                return self._error(e.code, e.message, e.status)
        try:
            access_key = self._authenticate()
        except AuthError as e:
            # anonymous access rides bucket policies when IAM is on
            if self.s3.iam is not None and e.code == "AccessDenied" and \
                    "no authentication" in e.message:
                access_key = ""
            else:
                return self._error(e.code, e.message, e.status)
        try:
            if self._maybe_forward_federated(access_key):
                return
            self._dispatch(access_key)
        except dt.ObjectAPIError as e:
            self._api_error(e)
        except AuthError as e:
            self._error(e.code, e.message, e.status)
        except (BadDigestError, SHA256MismatchError) as e:
            self._error("BadDigest", str(e), 400)
        except BrokenPipeError:
            # client went away mid-response; the half-written reply makes
            # this connection unusable for keep-alive
            self.close_connection = True
        except Exception as e:  # noqa: BLE001
            import traceback
            traceback.print_exc()
            self._error("InternalError", str(e), 500)

    #: federation forwarding: S3 action to enforce locally before the
    #: request is re-signed with cluster credentials — without this gate
    #: a scoped IAM user could escalate to root on the remote cluster
    _FWD_ACTIONS = {"GET": ("s3:GetObject", "s3:ListBucket"),
                    "HEAD": ("s3:GetObject", "s3:ListBucket"),
                    "PUT": ("s3:PutObject", "s3:CreateBucket"),
                    "POST": ("s3:PutObject", "s3:PutObject"),
                    "DELETE": ("s3:DeleteObject", "s3:DeleteBucket")}

    def _maybe_forward_federated(self, access_key: str) -> bool:
        """Federation forwarding (reference setBucketForwardingHandler,
        cmd/routers.go:73 + cmd/bucket-handlers.go DNS lookups): when the
        requested bucket is not local but the federation DNS says another
        cluster owns it, proxy the request there re-signed with this
        cluster's credentials (federated clusters share root creds).
        The caller's OWN policy gate runs first. Returns True when the
        response was served by the remote."""
        dns = self.s3.federation
        if dns is None or not self.bucket:
            return False
        if self.command == "PUT" and not self.key and \
                not self.query:
            return False  # bucket create: handled by put_bucket
        from ..utils import errors as st_errors
        try:
            self.s3.obj.get_bucket_info(self.bucket)
            return False  # local bucket: serve it here
        except (dt.BucketNotFound, st_errors.StorageError):
            pass
        if self.hdr.get("x-minio-tpu-forwarded"):
            # loop guard: a forwarded request that still isn't local here
            # (stale DNS pointing back at us) must fail, not re-forward
            return False
        owners = dns.lookup(self.bucket)
        if not owners or dns.is_mine(owners):
            return False  # unknown everywhere -> local NoSuchBucket
        obj_action, bkt_action = self._FWD_ACTIONS.get(
            self.command, ("s3:PutObject", "s3:PutObject"))
        if self.command == "POST" and "delete" in self.query:
            # multi-object delete rides POST: enforce the delete action,
            # not PutObject
            obj_action = bkt_action = "s3:DeleteObject"
        self._authorize(access_key,
                        obj_action if self.key else bkt_action)
        host, port = owners[0]
        import requests as rq
        # aws-chunked bodies: the wire length includes chunk framing; the
        # proxied body is the DECODED payload (the local handlers use the
        # same header, s3api _hash_reader)
        if self.hdr.get("x-amz-content-sha256", "") == STREAMING_PAYLOAD:
            size = int(self.hdr.get("x-amz-decoded-content-length",
                                    "0") or "0")
        else:
            size = int(self.hdr.get("content-length", "0") or "0")
        body = _LenReader(self._body_stream(size), size) if size else b""
        headers = {"host": f"{host}:{port}"}
        passthrough = ("content-type", "range", "if-match",
                       "if-none-match", "if-modified-since",
                       "if-unmodified-since", "content-md5")
        for k, v in self.hdr.items():
            if k in passthrough or k.startswith("x-amz-meta-"):
                headers[k] = v
        headers["x-minio-tpu-forwarded"] = "1"
        auth = self.s3.verifier.sign_request(
            self.s3.access_key, self.s3.secret_key, self.command,
            self.url_path, self.query, headers, UNSIGNED_PAYLOAD)
        headers["authorization"] = auth
        qs = urllib.parse.urlencode(
            [(k, v) for k, vs in self.query.items() for v in vs])
        url = f"http://{host}:{port}" \
              f"{urllib.parse.quote(self.url_path)}" + \
              (f"?{qs}" if qs else "")
        try:
            resp = rq.request(self.command, url, data=body,
                              headers=headers, timeout=30, stream=True)
        except Exception as e:  # noqa: BLE001 — owning cluster down
            self._error("ServiceUnavailable",
                        f"federated cluster unreachable: {e}", 503)
            return True
        self.send_response(resp.status_code)
        hop = {"connection", "transfer-encoding", "keep-alive"}
        length = resp.headers.get("Content-Length")
        for k, v in resp.headers.items():
            if k.lower() not in hop:
                self.send_header(k, v)
        if length is None:
            body_bytes = resp.content
            self.send_header("Content-Length", str(len(body_bytes)))
            self.end_headers()
            self.wfile.write(body_bytes)
        else:
            self.end_headers()
            for chunk in resp.iter_content(1 << 20):
                self.wfile.write(chunk)
        resp.close()
        return True

    def _internal_rpc(self, service: str, method: str):
        """Dispatch an internal RPC call (bearer-token auth, typed errors
        over headers — SURVEY.md A.7 wire shape)."""
        from ..dist.rpc import check_token, rpc_error_response
        auth = self.hdr.get("authorization", "")
        token = auth[len("Bearer "):] if auth.startswith("Bearer ") else ""
        if not check_token(self.s3.secret_key, token):
            return self._send(401, b"invalid rpc token", "text/plain")
        params = {k: v[0] for k, v in self.query.items()}
        body = self._read_body()
        # span propagation: an RPC that carried the caller's traceparent
        # joins that trace — storage/lock/peer spans recorded under this
        # fragment share the caller's trace_id and are stored locally
        # for the caller's ?trace_id=...&peers=1 merge
        from ..obs import spans as sp
        ctx_in = sp.parse_traceparent(self.hdr.get(sp.RPC_HEADER, ""))
        try:
            with sp.fragment(ctx_in, f"rpc.{service}.{method}",
                             node=f"{self.s3.address}:{self.s3.port}"):
                out = self.s3.internal[service].handle(method, params,
                                                       body)
        except Exception as e:  # noqa: BLE001
            return rpc_error_response(self, e)
        if out is not None and not isinstance(out, (bytes, bytearray)):
            # streaming method (live trace/console): chunked NDJSON with
            # keepalive newlines (A.7 framing)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            w = _ChunkedWriter(self.wfile)
            try:
                for chunk in out:
                    if chunk:
                        w.write(chunk)
            except Exception:  # noqa: BLE001 — client went away mid-stream
                self.close_connection = True
                return
            w.close()
            return
        self._send(200, out, "application/octet-stream")

    def _dispatch(self, access_key: str):
        m = self.command
        if not self.bucket:
            if m == "GET":
                return self.list_buckets(access_key)
            return self._error("MethodNotAllowed", "bad service op", 405)
        if not self.key:
            return self._bucket_op(m, access_key)
        return self._object_op(m, access_key)

    def _bucket_op(self, m: str, ak: str):
        s = self
        if m == "PUT":
            if s.has_q("versioning"):
                return s.put_versioning(ak)
            if s.has_q("tagging"):
                return s.put_bucket_tagging(ak)
            if s.has_q("policy"):
                return s.put_bucket_policy(ak)
            if s.has_q("notification"):
                return s.put_bucket_notification(ak)
            if s.has_q("lifecycle"):
                return s.put_bucket_lifecycle(ak)
            if s.has_q("replication"):
                return s.put_bucket_replication(ak)
            if s.has_q("object-lock"):
                return s.put_object_lock_config(ak)
            return s.put_bucket(ak)
        if m in ("GET", "HEAD"):
            if s.has_q("location"):
                return s._send(200, xu.location_xml(s.s3.region))
            if s.has_q("versioning"):
                return s.get_versioning(ak)
            if s.has_q("tagging"):
                return s.get_bucket_tagging(ak)
            if s.has_q("policy"):
                return s.get_bucket_policy(ak)
            if s.has_q("notification"):
                return s.get_bucket_notification(ak)
            if s.has_q("lifecycle"):
                return s.get_bucket_lifecycle(ak)
            if s.has_q("replication"):
                return s.get_bucket_replication(ak)
            if s.has_q("object-lock"):
                return s.get_object_lock_config(ak)
            if s.has_q("uploads"):
                return s.list_uploads(ak)
            if s.has_q("versions"):
                return s.list_versions(ak)
            if s.has_q("events") and m == "GET":
                return s.listen_bucket_notification(ak)
            if m == "HEAD":
                return s.head_bucket(ak)
            return s.list_objects(ak)
        if m == "DELETE":
            if s.has_q("tagging"):
                return s.delete_bucket_tagging(ak)
            if s.has_q("policy"):
                return s.delete_bucket_policy(ak)
            if s.has_q("lifecycle"):
                return s.delete_bucket_lifecycle(ak)
            if s.has_q("replication"):
                return s.delete_bucket_replication(ak)
            return s.delete_bucket(ak)
        if m == "POST":
            if s.has_q("delete"):
                return s.delete_multiple(ak)
        return s._error("MethodNotAllowed", f"bad bucket op {m}", 405)

    def post_policy_upload(self):
        """Browser POST upload with a signed policy document (reference
        PostPolicyBucketHandler, cmd/bucket-handlers.go +
        cmd/postpolicyform.go): the form's base64 policy is signed with
        the SigV4 signing key, conditions are enforced, then the file
        field becomes the object."""
        import base64
        import email.parser
        import email.policy as email_policy
        import json as jsonmod
        import re as remod

        from .auth import signing_key
        # the multipart parser is in-memory and the signature can only be
        # checked AFTER parsing, so an unauthenticated body must be capped
        # up front (DoS guard; env-tunable for big browser uploads)
        max_post = int(os.environ.get("MINIO_TPU_MAX_POST_SIZE",
                                      str(64 << 20)))
        declared = int(self.hdr.get("content-length", "0") or 0)
        if declared > max_post:
            raise dt.EntityTooLarge(self.bucket, "")
        body = self._read_body()
        if len(body) > max_post:
            raise dt.EntityTooLarge(self.bucket, "")
        blob = (b"Content-Type: " + self.hdr["content-type"].encode() +
                b"\r\n\r\n" + body)
        msg = email.parser.BytesParser(
            policy=email_policy.default).parsebytes(blob)
        fields: dict[str, str] = {}
        file_bytes = b""
        filename = ""
        for part in msg.iter_parts():
            cd = part.get("Content-Disposition", "")
            m = remod.search(r'name="([^"]*)"', cd)
            if not m:
                continue
            name = m.group(1)
            if name == "file":
                payload = part.get_payload(decode=True) or b""
                file_bytes = payload
                fm = remod.search(r'filename="([^"]*)"', cd)
                filename = fm.group(1) if fm else ""
            else:
                fields[name.lower()] = str(
                    part.get_payload(decode=True).decode(
                        "utf-8", "replace"))
        policy_b64 = fields.get("policy", "")
        if not policy_b64:
            return self._error("AccessDenied",
                               "POST upload requires a policy", 403)
        if fields.get("x-amz-algorithm", "") != "AWS4-HMAC-SHA256":
            return self._error("InvalidArgument",
                               "unsupported x-amz-algorithm", 400)
        cred = fields.get("x-amz-credential", "")
        try:
            ak, scope_date, region, _service, _term = cred.split("/")
        except ValueError:
            return self._error("InvalidArgument",
                               "malformed x-amz-credential", 400)
        secret = self.s3.lookup_secret(ak)
        if secret is None:
            return self._error("InvalidAccessKeyId",
                               "access key not found", 403)
        key = signing_key(secret, scope_date, region)
        import hmac as hmacmod
        sig = hmacmod.new(key, policy_b64.encode(),
                          hashlib.sha256).hexdigest()
        if not hmacmod.compare_digest(sig,
                                      fields.get("x-amz-signature", "")):
            return self._error("SignatureDoesNotMatch",
                               "policy signature mismatch", 403)
        try:
            policy = jsonmod.loads(base64.b64decode(policy_b64))
        except Exception:  # noqa: BLE001
            return self._error("InvalidPolicyDocument", "bad policy", 400)
        # expiration + conditions (cmd/postpolicyform.go)
        import datetime as dtmod
        exp = policy.get("expiration", "")
        try:
            exp_t = dtmod.datetime.fromisoformat(
                exp.replace("Z", "+00:00")).timestamp()
        except ValueError:
            return self._error("InvalidPolicyDocument",
                               "bad expiration", 400)
        import time as tmod
        if exp_t < tmod.time():
            return self._error("AccessDenied", "policy expired", 403)
        key_field = fields.get("key", "")
        if "${filename}" in key_field:
            key_field = key_field.replace("${filename}", filename)
        if not key_field:
            return self._error("InvalidArgument", "missing key field", 400)
        # every form field must be authorized by a policy condition
        # (cmd/postpolicyform.go checkPostPolicy) — otherwise a signed
        # grant for one key lets the holder inject arbitrary metadata
        covered = {"policy", "x-amz-signature", "file"}
        for cond in policy.get("conditions", []):
            if isinstance(cond, dict):
                covered.update(k.lower() for k in cond)
            elif isinstance(cond, list) and len(cond) == 3:
                covered.add(str(cond[1]).lstrip("$").lower())
        for fname in fields:
            if fname in covered or fname.startswith("x-ignore-"):
                continue
            return self._error(
                "AccessDenied",
                f"form field {fname!r} not covered by the policy", 403)
        for cond in policy.get("conditions", []):
            if isinstance(cond, dict):
                for ck, cv in cond.items():
                    got = self.bucket if ck == "bucket" else \
                        fields.get(ck.lower(), "")
                    if ck == "key":
                        got = key_field
                    if got != cv:
                        return self._error(
                            "AccessDenied",
                            f"policy condition failed on {ck}", 403)
            elif isinstance(cond, list) and len(cond) == 3:
                op, name, val = cond
                if op == "content-length-range":
                    try:
                        lo, hi = int(name), int(val)
                    except (TypeError, ValueError):
                        return self._error(
                            "InvalidPolicyDocument",
                            "bad content-length-range bounds", 400)
                    if not (lo <= len(file_bytes) <= hi):
                        return self._error(
                            "EntityTooLarge" if len(file_bytes) > hi
                            else "EntityTooSmall",
                            "content-length-range violated", 400)
                    continue
                name = str(name).lstrip("$").lower()
                got = key_field if name == "key" else (
                    self.bucket if name == "bucket"
                    else fields.get(name, ""))
                if op == "eq" and got != val:
                    return self._error(
                        "AccessDenied",
                        f"policy eq condition failed on {name}", 403)
                if op == "starts-with" and not str(got).startswith(val):
                    return self._error(
                        "AccessDenied",
                        f"policy starts-with failed on {name}", 403)
        self._authorize(ak, "s3:PutObject", self.bucket, key_field)
        self.key = key_field
        import io as iomod
        opts = self._opts()
        meta = {k: v for k, v in fields.items()
                if k.startswith("x-amz-meta-")}
        ct = fields.get("content-type", "")
        if ct:
            meta["content-type"] = ct
        # the POST path enforces the SAME server policies as PUT: size cap,
        # quota, object-lock defaults, transparent compression
        if len(file_bytes) > MAX_PUT_SIZE:
            raise dt.EntityTooLarge(self.bucket, key_field)
        self._check_quota(len(file_bytes))
        from ..bucket import objectlock as olock
        lock_enabled, lock_default = self._lock_ctx()
        meta.update(olock.check_put_headers(
            fields, self.bucket, key_field, lock_enabled, lock_default))
        hr = HashReader(iomod.BytesIO(file_bytes), len(file_bytes))
        stream, put_size = hr, len(file_bytes)
        from ..utils import compress as cz
        if cz.should_compress(key_field, ct):
            meta[cz.META_COMPRESSION] = cz.algo()
            meta[cz.META_ACTUAL_SIZE] = str(len(file_bytes))
            stream, put_size = cz.compress_reader(hr), -1
            opts.etag_source = hr
        opts.user_defined = meta
        oi = self.s3.obj.put_object(self.bucket, key_field, stream,
                                    put_size, opts)
        try:
            status = int(fields.get("success_action_status", "204") or 204)
        except ValueError:
            status = 204
        if status not in (200, 201, 204):
            status = 204
        self._send(status, headers={"ETag": f'"{oi.etag}"'})
        self._notify("s3:ObjectCreated:Post", oi)

    def _object_op(self, m: str, ak: str):
        s = self
        if m == "PUT":
            if s.has_q("partNumber") and s.has_q("uploadId"):
                return s.put_part(ak)
            if s.has_q("tagging"):
                return s.put_object_tagging(ak)
            if s.has_q("retention"):
                return s.put_object_retention(ak)
            if s.has_q("legal-hold"):
                return s.put_object_legal_hold(ak)
            if "x-amz-copy-source" in s.hdr:
                return s.copy_object(ak)
            return s.put_object(ak)
        if m == "GET":
            if s.has_q("uploadId"):
                return s.list_parts(ak)
            if s.has_q("tagging"):
                return s.get_object_tagging(ak)
            if s.has_q("retention"):
                return s.get_object_retention(ak)
            if s.has_q("legal-hold"):
                return s.get_object_legal_hold(ak)
            return s.get_object(ak)
        if m == "HEAD":
            return s.head_object(ak)
        if m == "DELETE":
            if s.has_q("uploadId"):
                return s.abort_upload(ak)
            if s.has_q("tagging"):
                return s.delete_object_tagging(ak)
            return s.delete_object(ak)
        if m == "POST":
            if s.has_q("uploads"):
                return s.initiate_upload(ak)
            if s.has_q("uploadId"):
                return s.complete_upload(ak)
            if s.has_q("select") or s.q("select-type"):
                return s.select_object_content(ak)
            if s.has_q("restore"):
                return s.restore_object(ak)
        return s._error("MethodNotAllowed", f"bad object op {m}", 405)

    def select_object_content(self, ak):
        """SelectObjectContent (reference cmd/object-handlers.go:96 ->
        pkg/s3select): run the SQL over the object and stream event-stream
        frames. Encrypted objects are decrypted first (the reference does
        the same through GetObjectNInfo's decrypting reader)."""
        self._authorize(ak, "s3:GetObject")
        from ..s3select import S3SelectRequest, parse_select, run_select
        from ..s3select.sql import SQLError
        body = self._read_body()
        try:
            req = S3SelectRequest.parse(body)
            # validate the SQL BEFORE reading the object (a bad expression
            # must 400 without paying the read; frames stream chunked
            # after the 200, so late errors can only abort mid-stream)
            parsed = parse_select(req.expression)
        except (ET.ParseError, SQLError) as e:
            return self._error("InvalidRequest", str(e), 400)
        opts = self._opts()
        oi = self.s3.obj.get_object_info(self.bucket, self.key, opts)
        sse = self._sse_read_ctx(oi)
        from ..utils import compress as cz
        import io as iomod
        sink = iomod.BytesIO()
        # BytesScanned = input consumed from storage (ciphertext /
        # compressed); the engine reports the decoded size as
        # BytesProcessed (s3select/message.py events)
        scanned = oi.size
        if sse:
            from ..crypto import DecryptWriter, enc_size
            oek, base_iv, plain_size, _, cipher = sse
            scanned = enc_size(plain_size)
            dw = DecryptWriter(sink, oek, base_iv, 0, 0, plain_size,
                               self.bucket, self.key, cipher=cipher)
            self.s3.obj.get_object(self.bucket, self.key, dw, 0, -1, opts)
            dw.finish()
        elif oi.internal.get(cz.META_COMPRESSION):
            # stored bytes are compressed: the SQL engine needs plaintext
            dz = cz.decompress_writer(
                oi.internal[cz.META_COMPRESSION], sink)
            self.s3.obj.get_object(self.bucket, self.key, dz, 0, -1, opts)
            dz.finish()
        else:
            self.s3.obj.get_object(self.bucket, self.key, sink, 0, -1, opts)
        raw = sink.getvalue()
        self.send_response(200)
        self.send_header("Content-Type",
                         "application/vnd.amazon.eventstream")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        out = _ChunkedWriter(self.wfile)
        try:
            run_select(req, raw, out, parsed=parsed,
                       scanned_bytes=scanned)
        except Exception:  # noqa: BLE001 — mid-stream failure: cut the
            self.close_connection = True  # connection, the client sees EOF
            return
        out.close()

    # --- HTTP verbs ---------------------------------------------------------

    def send_response(self, code, message=None):  # noqa: N802
        self._last_status = code
        if getattr(self, "_t_first", None) is None:
            import time as _time
            self._t_first = _time.perf_counter()  # TTFB anchor
        super().send_response(code, message)
        # every response carries the request id (= trace id) and host id
        # (reference setAmzRequestID middleware: x-amz-request-id +
        # x-amz-id-2 on all paths, streams and errors included) so
        # client-reported slowness joins server-side traces
        rid = getattr(self, "_request_id", "")
        if rid:
            self.send_header("x-amz-request-id", rid)
            self.send_header("x-amz-id-2", host_id())

    def _admit(self):
        """Admission control (minio_tpu.qos.admission) ahead of routing:
        object/control-plane requests pass the per-class token bucket +
        bounded-wait concurrency gate or are answered ``503 SlowDown`` +
        ``Retry-After`` (reference AmzRequestsDeadline behavior of
        cmd/handler-api.go, with S3-semantic backpressure instead of
        silent thread pile-up). Health, metrics, admin and internal-RPC
        planes are exempt — an overloaded server must stay observable.
        Returns (proceed, release_cb)."""
        from ..qos import classify_request
        adm = getattr(self.s3, "qos_admission", None)
        # stashed for the finish-side tail-sampling budget: the trace
        # must be judged under the SAME class it was admitted under
        cls = self._qos_class = classify_request(
            self.command, self.path, internal=self.s3.internal)
        if adm is None or cls is None:
            return True, None
        grant = adm.admit(cls)
        if grant.ok:
            return True, lambda: adm.release(grant)
        from ..obs import metrics as mx
        mx.inc("minio_tpu_qos_admission_rejects_total",
               reason=grant.reason, **{"class": cls})
        # parse url/headers so the surrounding observability plane (per-
        # API 503 counters, trace, audit) attributes this rejection like
        # any other response; the body stays unread — close instead of
        # leaving the keep-alive connection mid-stream
        self._parse()
        self.close_connection = True
        self._send(
            503,
            xu.error_xml(
                "SlowDown",
                "request rate/concurrency limit exceeded; reduce "
                "your request rate", self.url_path,
                request_id=getattr(self, "_request_id", ""),
                host_id=host_id()),
            headers={"Retry-After": adm.retry_after_header(grant)})
        return False, None

    def _span_exempt(self, path: str, query: str = "") -> bool:
        """Requests that never open a request-scoped trace:
        health/metrics probes (pure overhead), internal RPC (which
        instead JOINS the caller's trace via the traceparent header in
        _internal_rpc) — the same plane list admission control exempts
        — and long-poll streams (admin trace follows, bucket event
        listens) whose duration is client-chosen: they would breach any
        latency budget by design and churn genuinely slow traces out of
        the bounded store."""
        from ..qos.admission import plane_exempt
        if plane_exempt(path, internal=self.s3.internal):
            return True
        if path.startswith("/minio/admin/") and \
                path.rstrip("/").endswith("/trace"):
            return True
        if self.command == "GET" and "events=" in query and \
                not path.startswith("/minio/"):
            # ListenBucketNotification long-poll: a GET on a BUCKET
            # path with an events param — object GETs that merely carry
            # an events= value in some parameter stay traced
            parts = path.lstrip("/").split("/", 1)
            bucket_level = len(parts) < 2 or parts[1] == ""
            if bucket_level and "events" in urllib.parse.parse_qs(
                    query, keep_blank_values=True):
                return True
        return False

    def _handle(self):
        """Route one request wrapped in the observability plane
        (cmd/http-tracer.go httpTraceAll + cmd/http-stats.go): timing,
        metrics, trace pubsub, audit entry, request-scoped span root
        (obs/spans.py) with tail-sampled slow-trace capture. Admission
        rejections run INSIDE this wrapper so overload 503s land in the
        same per-API counters, trace stream and audit log as every
        other response."""
        import time as _time

        from ..obs import latency as _lt
        from ..obs import metrics as mx
        from ..obs import spans as sp
        from ..obs import trace as trc
        from ..obs.logger import log_sys
        self._last_status = 0
        self._t_first = None
        # the trace id IS the x-amz-request-id — minted before routing
        # so even admission 503s and parse errors carry it
        rid = sp.new_trace_id()
        self._request_id = rid
        root = tok = None
        raw_path, _, raw_query = self.path.partition("?")
        span_exempt = self._span_exempt(raw_path, raw_query)
        if sp.enabled() and not span_exempt:
            root, tok = sp.begin_request(rid)
        t0 = _time.perf_counter()
        sent_mark = getattr(self.wfile, "sent", 0)
        release = None
        from ..obs import profiler as _prof
        try:
            proceed, release = self._admit()
            # per-thread QoS tag (obs/profiler.py): contextvars are not
            # visible cross-thread, so the sampling profiler joins this
            # worker's samples to its admitted class + op through the
            # ident-keyed tag registry instead
            _prof.set_task_tag(
                getattr(self, "_qos_class", None) or "control",
                f"s3.{self.command.lower()}")
            if proceed:
                self._route()
        finally:
            _prof.clear_task_tag()
            if release is not None:
                release()
            try:
                self._drain_body()
            except Exception:  # noqa: BLE001
                self.close_connection = True
            dur = _time.perf_counter() - t0
            status = getattr(self, "_last_status", 0)
            path = getattr(self, "url_path", self.path)
            api = f"s3.{self.command}"
            if path.startswith("/minio/admin/"):
                api = "admin"
            elif path.startswith("/minio/"):
                api = "internal"
            api_detail = api
            try:
                mx.inc("minio_tpu_requests_total", api=api,
                       code=str(status))
                mx.observe("minio_tpu_request_duration_seconds", dur,
                           api=api)
                ttfb = (self._t_first or _time.perf_counter()) - t0
                if api.startswith("s3."):
                    # per-API-name family (reference metrics-v2 label
                    # scheme: api="getobject"-style)
                    name = self._api_name()
                    api_detail = f"s3.{name}"
                    mx.inc("minio_tpu_s3_requests_total", api=name)
                    if status >= 400:
                        mx.inc("minio_tpu_s3_requests_errors_total",
                               api=name)
                    mx.observe("minio_tpu_s3_ttfb_seconds", ttfb, api=name)
                    # per-API window whose worst sample keeps its trace
                    # id — `top/api` links the tail to a span tree.
                    # Only TRACED requests feed it: span-exempt
                    # long-polls (trace follows, event listens) would
                    # otherwise park multi-second traceless samples as
                    # the window's worst and blank the exemplar row
                    if root is not None:
                        _lt.observe("api", dur, 0,
                                    trace_id=rid if root.sampled else "",
                                    api=name)
                    # per-bucket analytics (obs/bucketstats): request
                    # counts, traffic bytes, TTFB/wall windows keyed by
                    # the BOUNDED registry — long-polls stay out for
                    # the same client-chosen-duration reason as spans
                    bkt = getattr(self, "bucket", "")
                    if bkt and not span_exempt:
                        from ..obs import bucketstats as _bs
                        sent = getattr(self.wfile, "sent", 0)
                        _bs.record_request(
                            bkt, name, status, dur, ttfb_s=ttfb,
                            bytes_in=getattr(self, "_consumed", 0),
                            bytes_out=max(0, sent - sent_mark))
                elif api == "admin" and root is not None:
                    _lt.observe("api", dur, 0,
                                trace_id=rid if root.sampled else "",
                                api="admin")
                if api != "internal":
                    info = trc.TraceInfo(
                        node=f"{self.s3.address}:{self.s3.port}",
                        func=api, method=self.command,
                        path=path, query=getattr(self, "raw_query", ""),
                        status=status, duration_s=dur, ttfb_s=ttfb,
                        input_bytes=int(getattr(self, "hdr", {}).get(
                            "content-length", "0") or 0),
                        remote=self.client_address[0],
                        trace_id=rid,
                        span_id=root.span_id if root is not None else "")
                    trc.publish(info)
                    # audit entries join traces by trace_id/request_id
                    # and carry the response outcome (status + duration
                    # already ride the trace record)
                    entry = info.to_dict()
                    entry["request_id"] = rid
                    entry["api"] = api_detail
                    log_sys().audit(entry)
                # SLO plane LAST (it may take the config-registry lock
                # resolving objectives): admitted-class requests (and
                # admission 503s) burn their class's error budget;
                # exempt planes (health/metrics/admin/internal-RPC)
                # carry no objective so qcls is None for them, and
                # span-exempt long-polls (trace follows, event
                # listens) stay out — their duration is CLIENT-chosen,
                # so every poll would read as a multi-second latency
                # breach on an idle server (same rule as the per-API
                # window above, but independent of spans being on)
                qcls = getattr(self, "_qos_class", None)
                if qcls is not None and not span_exempt:
                    from ..obs import slo as _slo
                    _slo.record(
                        qcls, dur, status=status,
                        trace_id=rid if root is not None and
                        root.sampled else "",
                        bucket=getattr(self, "bucket", "")
                        if api.startswith("s3.") else "")
            except Exception:  # noqa: BLE001 — obs must never break serving
                pass
            if root is not None:
                try:
                    cls = getattr(self, "_qos_class", None) or "control"
                    sp.finish_request(
                        root, tok, name=api_detail, method=self.command,
                        path=path, status=status, duration_s=dur,
                        cls=cls,
                        node=f"{self.s3.address}:{self.s3.port}",
                        remote=self.client_address[0])
                    kept = sp.store().get(rid) if root.sampled else None
                    if kept is not None and any(
                            s.get("name", "").startswith("rpc.")
                            for s in kept.get("spans", ())):
                        # the trace was KEPT and fanned out over RPC:
                        # snapshot peer fragments now, before their
                        # small LRUs churn them out (bounded background
                        # worker — the response is already sent)
                        peers = getattr(self.s3, "peers",
                                        lambda: [])()
                        if peers:
                            sp.schedule_collect(rid, peers)
                except Exception:  # noqa: BLE001
                    pass

    def do_GET(self):  # noqa: N802
        self._handle()

    def do_PUT(self):  # noqa: N802
        self._handle()

    def do_POST(self):  # noqa: N802
        self._handle()

    def do_DELETE(self):  # noqa: N802
        self._handle()

    def do_HEAD(self):  # noqa: N802
        self._handle()

    # --- service ------------------------------------------------------------

    def list_buckets(self, ak):
        self._authorize(ak, "s3:ListAllMyBuckets")
        buckets = self.s3.obj.list_buckets()
        if self.s3.federation is not None:
            # the federated namespace is the union of every cluster's
            # buckets (cmd/bucket-handlers.go ListBuckets with etcd)
            have = {b.name for b in buckets}
            for name in sorted(self.s3.federation.list_buckets()):
                if name not in have:
                    buckets.append(dt.BucketInfo(name=name))
        self._send(200, xu.list_buckets_xml(buckets))

    # --- bucket -------------------------------------------------------------

    def put_bucket(self, ak):
        self._authorize(ak, "s3:CreateBucket")
        self.s3.create_bucket(
            self.bucket,
            object_lock=self.hdr.get(
                "x-amz-bucket-object-lock-enabled", "") == "true")
        self._send(200, headers={"Location": f"/{self.bucket}"})

    def head_bucket(self, ak):
        self._authorize(ak, "s3:ListBucket")
        self.s3.obj.get_bucket_info(self.bucket)
        self._send(200)

    def delete_bucket(self, ak):
        self._authorize(ak, "s3:DeleteBucket")
        force = self.hdr.get("x-minio-force-delete", "") == "true"
        self.s3.remove_bucket(self.bucket, force=force)
        self._send(204)

    @staticmethod
    def _display_sizes(r):
        """Listings must report the same size GET/HEAD do: for encrypted
        or compressed objects that is the plaintext size, not the stored
        stream length."""
        from ..bucket import transition as tx
        from ..crypto import META_SCHEME, plain_size_of
        from ..utils import compress as cz
        for oi in r.objects:
            if oi.internal.get(META_SCHEME):
                oi.size = plain_size_of(oi.internal, oi.size)
            elif oi.internal.get(cz.META_COMPRESSION):
                oi.size = oi.actual_size
            elif tx.is_transitioned(oi) and oi.size == 0:
                oi.size = tx.transitioned_size(oi)
        return r

    def list_objects(self, ak):
        self._authorize(ak, "s3:ListBucket")
        prefix = self.q("prefix")
        delimiter = self.q("delimiter")
        max_keys = min(int(self.q("max-keys", "1000") or "1000"), 10_000)
        if self.q("list-type") == "2":
            marker = self.q("continuation-token") or self.q("start-after")
            r = self._display_sizes(self.s3.obj.list_objects(
                self.bucket, prefix, marker, delimiter, max_keys))
            return self._send(200, xu.list_objects_v2_xml(
                self.bucket, prefix, delimiter, max_keys, r,
                continuation_token=self.q("continuation-token")))
        marker = self.q("marker")
        r = self._display_sizes(self.s3.obj.list_objects(
            self.bucket, prefix, marker, delimiter, max_keys))
        self._send(200, xu.list_objects_v1_xml(
            self.bucket, prefix, delimiter, marker, max_keys, r))

    def list_versions(self, ak):
        self._authorize(ak, "s3:ListBucketVersions")
        prefix = self.q("prefix")
        delimiter = self.q("delimiter")
        max_keys = min(int(self.q("max-keys", "1000") or "1000"), 10_000)
        r = self._display_sizes(self.s3.obj.list_object_versions(
            self.bucket, prefix, self.q("key-marker"),
            self.q("version-id-marker"), delimiter, max_keys))
        self._send(200, xu.list_versions_xml(
            self.bucket, prefix, delimiter, max_keys, r))

    def put_versioning(self, ak):
        self._authorize(ak, "s3:PutBucketVersioning")
        self.s3.obj.get_bucket_info(self.bucket)
        body = self._read_body()
        enabled = xu.parse_versioning(body)
        was = self.s3.bucket_meta.get(self.bucket)
        if was.object_lock_enabled and not enabled:
            # suspending versioning would let WORM-retained versions be
            # hard-deleted via versionless deletes (AWS forbids changing
            # versioning state on object-lock buckets)
            raise dt.InvalidRequest(
                self.bucket, "",
                "cannot suspend versioning on an object-lock bucket")
        self.s3.bucket_meta.update(
            self.bucket, versioning_enabled=enabled,
            versioning_suspended=not enabled and
            (was.versioning_enabled or was.versioning_suspended))
        self._send(200)

    def get_versioning(self, ak):
        self._authorize(ak, "s3:GetBucketVersioning")
        self.s3.obj.get_bucket_info(self.bucket)
        meta = self.s3.bucket_meta.get(self.bucket)
        self._send(200, xu.versioning_xml(meta.versioning_enabled,
                                          meta.versioning_suspended))

    def put_bucket_tagging(self, ak):
        self._authorize(ak, "s3:PutBucketTagging")
        self.s3.obj.get_bucket_info(self.bucket)
        tags = xu.parse_tagging(self._read_body())
        self.s3.bucket_meta.update(self.bucket, tagging=tags)
        self._send(200)

    def get_bucket_tagging(self, ak):
        self._authorize(ak, "s3:GetBucketTagging")
        meta = self.s3.bucket_meta.get(self.bucket)
        if not meta.tagging:
            return self._error("NoSuchTagSet", "no tags", 404)
        self._send(200, xu.tagging_xml(meta.tagging))

    def delete_bucket_tagging(self, ak):
        self._authorize(ak, "s3:PutBucketTagging")
        self.s3.bucket_meta.update(self.bucket, tagging={})
        self._send(204)

    def put_bucket_policy(self, ak):
        self._authorize(ak, "s3:PutBucketPolicy")
        self.s3.obj.get_bucket_info(self.bucket)
        self.s3.bucket_meta.update(self.bucket,
                                   policy_json=self._read_body())
        self._send(204)

    def get_bucket_policy(self, ak):
        self._authorize(ak, "s3:GetBucketPolicy")
        meta = self.s3.bucket_meta.get(self.bucket)
        if not meta.policy_json:
            return self._error("NoSuchBucketPolicy", "no policy", 404)
        self._send(200, meta.policy_json, "application/json")

    def delete_bucket_policy(self, ak):
        self._authorize(ak, "s3:DeleteBucketPolicy")
        self.s3.bucket_meta.update(self.bucket, policy_json=b"")
        self._send(204)

    def listen_bucket_notification(self, ak):
        """Live event stream (the reference's ListenBucketNotification
        minio extension, cmd/bucket-notification-handlers.go): GET
        /bucket?events=<pattern>&prefix=&suffix= streams matching events
        as JSON lines over chunked encoding; blank keep-alive lines mark
        liveness. Needs no stored notification config — the filters ride
        the request. ?timeout bounds the stream (tests; clients normally
        hold it open)."""
        self._authorize(ak, "s3:ListenBucketNotification")
        self.s3.obj.get_bucket_info(self.bucket)
        # listening needs the event plane; attach it lazily with no
        # targets (queues only exist per target, listeners are free)
        notifier = self.s3.ensure_notifier()
        import json as _json
        import queue as qmod
        import time as _time
        events = tuple(v for vs in self.query.get("events", [])
                       for v in (vs.split(",") if vs else [])) or ("s3:*",)
        prefix = self.q("prefix")
        suffix = self.q("suffix")
        try:
            timeout = float(self.q("timeout", "86400") or "86400")
        except ValueError:
            timeout = -1.0
        if not timeout > 0:  # rejects 0, negatives AND NaN
            raise dt.InvalidRequest(self.bucket, "",
                                    "invalid listen timeout")
        sub = notifier.listen(self.bucket, prefix, suffix, events)
        try:  # from here every exit must unlisten, or the dead
            # subscription keeps collecting events forever
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            out = _ChunkedWriter(self.wfile)
            deadline = _time.monotonic() + timeout
            while _time.monotonic() < deadline:
                try:
                    rec = sub.q.get(timeout=min(
                        5.0, max(0.0, deadline - _time.monotonic())))
                except qmod.Empty:
                    out.write(b" \n")  # keep-alive (reference sends one)
                    self.wfile.flush()
                    continue
                out.write((_json.dumps(
                    {"Records": [rec]},
                    separators=(",", ":")) + "\n").encode())
                self.wfile.flush()
            out.close()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away: normal end of a listen stream
        finally:
            notifier.unlisten(sub)
            self.close_connection = True

    def put_bucket_notification(self, ak):
        self._authorize(ak, "s3:PutBucketNotification")
        self.s3.obj.get_bucket_info(self.bucket)
        body = self._read_body()
        from ..event import parse_notification_xml
        try:
            parsed = parse_notification_xml(body)
        except Exception:  # noqa: BLE001 — malformed XML
            return self._error("MalformedXML",
                               "invalid notification configuration", 400)
        if self.s3._notifier is not None and self.s3._notifier.targets:
            # a listener-only notifier (no configured targets) must not
            # reject every ARN — matching the pre-notifier behavior of
            # accepting and persisting the config
            unknown = self.s3._notifier.unknown_arns(parsed)
            if unknown:
                return self._error(
                    "InvalidArgument",
                    f"unknown notification target ARN(s): "
                    f"{', '.join(unknown)}", 400)
        self.s3.bucket_meta.update(self.bucket, notification_xml=body)
        if self.s3._notifier is not None:
            self.s3._notifier.invalidate(self.bucket)
        self._send(200)

    def get_bucket_notification(self, ak):
        self._authorize(ak, "s3:GetBucketNotification")
        meta = self.s3.bucket_meta.get(self.bucket)
        body = meta.notification_xml or \
            b'<?xml version="1.0" encoding="UTF-8"?>' \
            b'<NotificationConfiguration ' \
            b'xmlns="http://s3.amazonaws.com/doc/2006-03-01/"/>'
        self._send(200, body)

    def put_bucket_lifecycle(self, ak):
        self._authorize(ak, "s3:PutLifecycleConfiguration")
        self.s3.obj.get_bucket_info(self.bucket)
        self.s3.bucket_meta.update(self.bucket,
                                   lifecycle_xml=self._read_body())
        self._send(200)

    def get_bucket_lifecycle(self, ak):
        self._authorize(ak, "s3:GetLifecycleConfiguration")
        meta = self.s3.bucket_meta.get(self.bucket)
        if not meta.lifecycle_xml:
            return self._error("NoSuchLifecycleConfiguration",
                               "no lifecycle", 404)
        self._send(200, meta.lifecycle_xml)

    def delete_bucket_lifecycle(self, ak):
        self._authorize(ak, "s3:PutLifecycleConfiguration")
        self.s3.bucket_meta.update(self.bucket, lifecycle_xml=b"")
        self._send(204)

    def put_bucket_replication(self, ak):
        """PUT ?replication (reference PutBucketReplicationConfigHandler):
        rules validate before persisting — a rule without a destination
        would charge obligations nothing can ever pay."""
        self._authorize(ak, "s3:PutReplicationConfiguration")
        self.s3.obj.get_bucket_info(self.bucket)
        body = self._read_body()
        from ..bucket import replicate as repl
        try:
            repl.validate_replication(body)
        except (ET.ParseError, ValueError) as e:
            return self._error("MalformedXML", str(e), 400)
        self.s3.bucket_meta.update(self.bucket, replication_xml=body)
        self._send(200)

    def get_bucket_replication(self, ak):
        self._authorize(ak, "s3:GetReplicationConfiguration")
        meta = self.s3.bucket_meta.get(self.bucket)
        if not meta.replication_xml:
            return self._error("ReplicationConfigurationNotFoundError",
                               "no replication config", 404)
        self._send(200, meta.replication_xml)

    def delete_bucket_replication(self, ak):
        self._authorize(ak, "s3:PutReplicationConfiguration")
        self.s3.bucket_meta.update(self.bucket, replication_xml=b"")
        self._send(204)

    def delete_multiple(self, ak):
        self._authorize(ak, "s3:DeleteObject")
        self._last_ak = ak
        objs, quiet = xu.parse_delete_objects(self._read_body())
        versioned = self.s3.bucket_meta.versioning_enabled(self.bucket)
        # WORM: version deletes under retention/legal hold are refused
        # per key, not whole-request (reference DeleteObjects behavior)
        meta = self.s3.bucket_meta.get(self.bucket)
        locked_errs: list[tuple[int, str, str, BaseException]] = []
        if meta.object_lock_enabled:
            allowed = []
            for idx, obj in enumerate(objs):
                vid = "" if isinstance(obj, str) else obj.get(
                    "version_id", "")
                name = obj if isinstance(obj, str) else obj["object"]
                if vid:
                    try:
                        self._check_delete_lock(ObjectOptions(
                            version_id=vid, versioned=versioned), key=name)
                    except dt.ObjectAPIError as e:
                        # keep key+version so the <Error> entry names what
                        # was refused
                        locked_errs.append((idx, name, vid, e))
                        continue
                allowed.append(obj)
            objs = allowed
        deleted, errs = self.s3.obj.delete_objects(
            self.bucket, objs, ObjectOptions(versioned=versioned))
        for idx, name, vid, e in locked_errs:
            deleted.insert(idx, dt.DeletedObject(object_name=name,
                                                 version_id=vid))
            errs.insert(idx, e)
        ok_deleted = [d for d, e in zip(deleted, errs) if e is None]
        if quiet:
            # quiet mode reports only failures
            pairs = [(d, e) for d, e in zip(deleted, errs) if e is not None]
            deleted = [d for d, _ in pairs]
            errs = [e for _, e in pairs]
        self._send(200, xu.delete_result_xml(deleted, errs))
        self._notify_each("s3:ObjectRemoved:Delete", ok_deleted)

    def _notify_each(self, event, deleted):
        if self.s3.notify is None:
            return
        for d in deleted:
            if d is not None:
                self.s3.notify(event, self.bucket,
                               dt.ObjectInfo(bucket=self.bucket,
                                             name=d.object_name))

    # --- object -------------------------------------------------------------

    def _opts(self, versioned=None) -> ObjectOptions:
        if versioned is None:
            versioned = self.s3.bucket_meta.versioning_enabled(self.bucket)
        return ObjectOptions(version_id=self.q("versionId"),
                             versioned=versioned)

    def put_object(self, ak):
        self._authorize(ak, "s3:PutObject")
        size = int(self.hdr.get("content-length", "-1") or "-1")
        if self.hdr.get("x-amz-content-sha256", "") == STREAMING_PAYLOAD:
            size = int(self.hdr.get("x-amz-decoded-content-length",
                                    str(size)))
        if size < 0:
            # unbounded socket reads hang keep-alive connections
            return self._error("MissingContentLength",
                               "Content-Length required", 411)
        if size > MAX_PUT_SIZE:
            raise dt.EntityTooLarge(self.bucket, self.key)
        user_defined = self._user_meta()
        # object lock: validate headers / apply the bucket default
        from ..bucket import objectlock as olock
        lock_enabled, lock_default = self._lock_ctx()
        user_defined.update(olock.check_put_headers(
            self.hdr, self.bucket, self.key, lock_enabled, lock_default))
        # quota (reference cmd/bucket-quota.go: enforced from the data
        # usage snapshot, so it trails the scanner like the reference)
        self._check_quota(size)
        hr = self._hash_reader(size)
        from ..crypto import parse_sse_headers
        sse = parse_sse_headers(self.hdr, self.bucket, self.key)
        stream, put_size = hr, size
        sse_resp = {}
        opts = self._opts()
        if sse is not None:
            stream, put_size, sse_resp = self._encrypt_setup(
                sse, hr, size, user_defined)
        else:
            from ..utils import compress as cz
            if cz.should_compress(self.key,
                                  user_defined.get("content-type", "")):
                # compressed length is unknown up front: the object layer
                # streams to EOF (size=-1) and records the stored length;
                # ETag stays the PLAINTEXT md5 via etag_source
                user_defined[cz.META_COMPRESSION] = cz.algo()
                user_defined[cz.META_ACTUAL_SIZE] = str(size)
                stream, put_size = cz.compress_reader(hr), -1
                opts.etag_source = hr
        # replication charged at PUT: the status lands IN xl.meta with
        # the write itself (no post-write meta update to lose in a
        # crash window) — the notify chain enqueues the debt
        rs = getattr(self.s3, "replication_sys", None)
        if rs is not None and rs.heads_up(self.bucket, self.key) is not None:
            from ..bucket import replicate as repl
            user_defined[repl.META_REP_STATUS] = repl.PENDING
        opts.user_defined = user_defined
        oi = self.s3.obj.put_object(self.bucket, self.key, stream, put_size,
                                    opts)
        if stream is not hr:
            # everything downstream (response, event records) speaks
            # plaintext sizes; the stored (encrypted/compressed) length is
            # an internal detail
            oi.size = size
        self._send(200, headers={
            "ETag": f'"{oi.etag}"',
            "x-amz-version-id": oi.version_id or None,
            **sse_resp})
        self._notify("s3:ObjectCreated:Put", oi)

    def _encrypt_setup(self, sse, hr, size: int, user_defined: dict):
        """Envelope setup for a PUT (cmd/encryption-v1.go EncryptRequest):
        random OEK sealed under the request key (SSE-C) or a KMS data key
        (SSE-S3); internal metadata records everything a reader needs
        except the secret itself. Returns (cipher stream, encrypted size,
        response headers)."""
        import base64
        import secrets

        from ..crypto import (EncryptReader, enc_size, get_kms,
                              seal_object_key, sse_kms_context)
        from ..crypto.sse import (META_CIPHER, META_IV, META_KEY_MD5,
                                  META_KMS_BLOB, META_KMS_CONTEXT,
                                  META_KMS_KEY_ID, META_PLAIN_SIZE,
                                  META_SCHEME, META_SEALED, default_cipher)
        oek = secrets.token_bytes(32)
        base_iv = secrets.token_bytes(12)
        cipher = default_cipher()
        user_defined[META_SCHEME] = sse.scheme
        user_defined[META_IV] = base64.b64encode(base_iv).decode()
        user_defined[META_PLAIN_SIZE] = str(size)
        user_defined[META_CIPHER] = cipher
        if sse.scheme == "C":
            sealed = seal_object_key(oek, sse.key, self.bucket, self.key,
                                     cipher=cipher)
            user_defined[META_KEY_MD5] = sse.key_md5
            resp = {
                "x-amz-server-side-encryption-customer-algorithm": "AES256",
                "x-amz-server-side-encryption-customer-key-MD5":
                    sse.key_md5}
        elif sse.scheme == "KMS":
            kms = get_kms()
            key_id = sse.kms_key_id or kms.key_id
            ctx = sse_kms_context(self.bucket, self.key, sse.kms_context)
            dk, blob = self._kms_generate(kms, ctx, key_id)
            sealed = seal_object_key(oek, dk, self.bucket, self.key,
                                     cipher=cipher)
            user_defined[META_KMS_BLOB] = base64.b64encode(blob).decode()
            user_defined[META_KMS_KEY_ID] = key_id
            if sse.kms_context:
                user_defined[META_KMS_CONTEXT] = base64.b64encode(
                    sse.kms_context.encode()).decode()
            resp = {"x-amz-server-side-encryption": "aws:kms",
                    "x-amz-server-side-encryption-aws-kms-key-id": key_id}
        else:
            kms = get_kms()
            dk, blob = self._kms_generate(kms, f"{self.bucket}/{self.key}")
            sealed = seal_object_key(oek, dk, self.bucket, self.key,
                                     cipher=cipher)
            user_defined[META_KMS_BLOB] = base64.b64encode(blob).decode()
            resp = {"x-amz-server-side-encryption": "AES256"}
        user_defined[META_SEALED] = base64.b64encode(sealed).decode()
        return (EncryptReader(hr, oek, base_iv, cipher=cipher),
                enc_size(size), resp)

    def _kms_generate(self, kms, ctx: str, key_id: str = ""):
        """generate_key with a KMS outage surfaced as a retryable 503
        (matching the read path) instead of a generic 500."""
        from ..crypto import KMSUnreachable
        try:
            return kms.generate_key(ctx, key_id=key_id)
        except KMSUnreachable as e:
            raise dt.KMSNotAvailable(self.bucket, self.key,
                                     extra=str(e)) from None

    def _sse_read_ctx(self, oi):
        """For an encrypted object: unseal the OEK using this request's
        credentials and return (oek, base_iv, plain_size, response
        headers, package cipher); None for plaintext objects. SSE-C
        requires the customer key headers on GET/HEAD (matching
        fingerprint — a wrong key MD5 403s BEFORE any package is read or
        opened), SSE-S3 unseals via the KMS (cmd/encryption-v1.go
        DecryptRequest)."""
        import base64

        from ..crypto import (get_kms, parse_sse_headers, sse_kms_context,
                              unseal_object_key)
        from ..crypto.sse import (META_IV, META_KEY_MD5, META_KMS_BLOB,
                                  META_KMS_CONTEXT, META_KMS_KEY_ID,
                                  META_PLAIN_SIZE, META_SCHEME, META_SEALED,
                                  cipher_of)
        from ..crypto import plain_size_of
        scheme = oi.internal.get(META_SCHEME, "")
        if not scheme:
            return None
        sealed = base64.b64decode(oi.internal.get(META_SEALED, ""))
        base_iv = base64.b64decode(oi.internal.get(META_IV, ""))
        plain_size = plain_size_of(oi.internal, oi.size)
        cipher = cipher_of(oi.internal)
        if scheme == "C":
            req = parse_sse_headers(self.hdr, self.bucket, self.key)
            if req is None or req.scheme != "C":
                raise dt.SSEEncryptedObject(self.bucket, self.key)
            if req.key_md5 != oi.internal.get(META_KEY_MD5, ""):
                raise dt.SSEKeyMismatch(self.bucket, self.key)
            oek = unseal_object_key(sealed, req.key, self.bucket, self.key,
                                    cipher=cipher)
            resp = {
                "x-amz-server-side-encryption-customer-algorithm": "AES256",
                "x-amz-server-side-encryption-customer-key-MD5":
                    req.key_md5}
        elif scheme == "KMS":
            blob = base64.b64decode(oi.internal.get(META_KMS_BLOB, ""))
            key_id = oi.internal.get(META_KMS_KEY_ID, "")
            stored_ctx = ""
            if oi.internal.get(META_KMS_CONTEXT, ""):
                stored_ctx = base64.b64decode(
                    oi.internal[META_KMS_CONTEXT]).decode()
            ctx = sse_kms_context(self.bucket, self.key, stored_ctx)
            from ..crypto import KMSUnreachable
            try:
                dk = get_kms().unseal(blob, ctx, key_id=key_id)
            except KMSUnreachable as e:
                # transient KMS outage — not a wrong-key condition
                raise dt.KMSNotAvailable(self.bucket, self.key,
                                         extra=str(e)) from None
            except Exception:  # noqa: BLE001 — rotated/deleted master key
                raise dt.SSEKeyMismatch(self.bucket, self.key) from None
            oek = unseal_object_key(sealed, dk, self.bucket, self.key,
                                    cipher=cipher)
            resp = {"x-amz-server-side-encryption": "aws:kms",
                    "x-amz-server-side-encryption-aws-kms-key-id": key_id}
        else:
            from ..crypto import KMSUnreachable
            blob = base64.b64decode(oi.internal.get(META_KMS_BLOB, ""))
            try:
                dk = get_kms().unseal(blob, f"{self.bucket}/{self.key}")
            except KMSUnreachable as e:
                raise dt.KMSNotAvailable(self.bucket, self.key,
                                         extra=str(e)) from None
            except Exception:  # noqa: BLE001 — rotated/wrong master key
                raise dt.SSEKeyMismatch(self.bucket, self.key) from None
            oek = unseal_object_key(sealed, dk, self.bucket, self.key,
                                    cipher=cipher)
            resp = {"x-amz-server-side-encryption": "AES256"}
        return oek, base_iv, plain_size, resp, cipher

    def _hash_reader(self, size: int) -> HashReader:
        """Body reader verifying Content-MD5 / x-amz-content-sha256 on the
        fly — shared by PutObject and UploadPart so the two paths can't
        diverge."""
        sha = self.hdr.get("x-amz-content-sha256", "")
        sha_hex = sha if sha and sha not in (
            UNSIGNED_PAYLOAD, STREAMING_PAYLOAD) else ""
        md5_b64 = self.hdr.get("content-md5", "")
        md5_hex = ""
        if md5_b64:
            import base64
            import binascii
            try:
                decoded = base64.b64decode(md5_b64, validate=True)
            except (binascii.Error, ValueError) as e:
                raise dt.InvalidDigest(self.bucket, self.key) from e
            if len(decoded) != 16:
                raise dt.InvalidDigest(self.bucket, self.key)
            md5_hex = decoded.hex()
        return HashReader(self._body_stream(size), size, md5_hex, sha_hex)

    def _user_meta(self) -> dict[str, str]:
        out = {}
        ct = self.hdr.get("content-type")
        if not ct and self.key:
            # extension-based detection via the curated mimedb table
            # (reference pkg/mimedb; deterministic across containers,
            # stdlib mimetypes as fallback for exotic extensions)
            from ..utils.mimedb import content_type
            ct = content_type(self.key)
        if ct:
            out["content-type"] = ct
        for k, v in self.hdr.items():
            if k.startswith("x-amz-meta-"):
                out[k] = v
        for k in ("cache-control", "content-disposition",
                  "content-encoding", "content-language", "expires"):
            if k in self.hdr:
                out[k] = self.hdr[k]
        return out

    def _notify(self, event, oi):
        if self.s3.notify is not None:
            self.s3.notify(event, self.bucket, oi)

    def _obj_headers(self, oi) -> dict:
        h = {
            "ETag": f'"{oi.etag}"',
            "Last-Modified": xu.http_date(oi.mod_time),
            "Content-Type": oi.content_type or "application/octet-stream",
            "Accept-Ranges": "bytes",
            "x-amz-version-id": oi.version_id or None,
        }
        for k, v in oi.user_defined.items():
            if k.startswith("x-amz-meta-") or \
                    k.startswith("x-amz-object-lock-") or k in (
                    "cache-control", "content-disposition",
                    "content-encoding", "content-language", "expires"):
                h[k] = v
        return h

    def _parse_range(self, total: int):
        rng = self.hdr.get("range", "")
        if not rng.startswith("bytes="):
            return None
        spec = rng[len("bytes="):].split(",")[0].strip()
        start_s, _, end_s = spec.partition("-")
        try:
            if start_s == "":
                n = int(end_s)
                if n == 0:
                    raise dt.InvalidRange(self.bucket, self.key)
                start, end = max(0, total - n), total - 1
            else:
                start = int(start_s)
                end = int(end_s) if end_s else total - 1
        except ValueError:
            return None
        if start >= total or end < start:
            raise dt.InvalidRange(self.bucket, self.key)
        return start, min(end, total - 1)

    def get_object(self, ak):
        self._authorize(ak, "s3:GetObject")
        opts = self._opts()
        try:
            oi = self.s3.obj.get_object_info(self.bucket, self.key, opts)
        except dt.ObjectNotFound:
            # replication proxy: serve from the bucket's remote target
            # when the object hasn't replicated back yet
            pool = getattr(self.s3, "replication", None)
            res = pool.proxy_get(self.bucket, self.key,
                                 self.hdr.get("range", "")) \
                if pool is not None else None
            if res is None:
                raise
            status, chunks, hdrs, clen = res
            self.send_response(status)
            for k, v in hdrs.items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(clen))
            self.send_header("x-minio-proxied-from-target", "true")
            self.end_headers()
            for chunk in chunks:  # streams: never fully resident
                if chunk:
                    self.wfile.write(chunk)
            return
        self._check_preconditions(oi)
        from ..bucket import transition as tx
        if tx.is_transitioned(oi) and oi.size == 0:
            # stub: read through from the tier (cmd/bucket-lifecycle.go
            # getTransitionedObjectReader)
            return self._get_transitioned(oi)
        sse = self._sse_read_ctx(oi)
        from ..utils import compress as cz
        compressed = oi.internal.get(cz.META_COMPRESSION, "")
        logical_size = sse[2] if sse else (
            oi.actual_size if compressed else oi.size)
        rng = self._parse_range(logical_size) if logical_size > 0 else None
        headers = self._obj_headers(oi)
        if sse:
            headers.update(sse[3])
        if rng is None:
            offset, length = 0, logical_size
            status = 200
        else:
            offset, length = rng[0], rng[1] - rng[0] + 1
            status = 206
            headers["Content-Range"] = \
                f"bytes {rng[0]}-{rng[1]}/{logical_size}"
        self.send_response(status)
        for k, v in headers.items():
            if v:
                self.send_header(k, v)
        self.send_header("Content-Length", str(length))
        self.end_headers()
        if length > 0:
            if sse:
                from ..crypto import DecryptWriter, decrypt_range_bounds
                oek, base_iv, plain_size, _, cipher = sse
                enc_off, enc_len, seq0, skip = decrypt_range_bounds(
                    offset, length, plain_size)
                dw = DecryptWriter(self.wfile, oek, base_iv, seq0, skip,
                                   length, self.bucket, self.key,
                                   cipher=cipher)
                if enc_len > 0:
                    self.s3.obj.get_object(self.bucket, self.key, dw,
                                           enc_off, enc_len, opts)
                dw.finish()
            elif compressed:
                # inflate the whole stored stream, trim to the requested
                # plaintext range (reference compressed-range behavior)
                dz = cz.decompress_writer(compressed, self.wfile,
                                          skip=offset, limit=length)
                self.s3.obj.get_object(self.bucket, self.key, dz, 0, -1,
                                       opts)
                dz.finish()
            else:
                self.s3.obj.get_object(self.bucket, self.key, self.wfile,
                                       offset, length, opts)
        self._notify("s3:ObjectAccessed:Get", oi)

    def _get_transitioned(self, oi):
        from ..bucket import transition as tx
        try:
            data = self.s3.transition.read(oi)
        except Exception:  # noqa: BLE001 — tier unreachable
            return self._error("InvalidObjectState",
                               "transitioned object's tier unavailable",
                               403)
        rng = self._parse_range(len(data)) if data else None
        headers = self._obj_headers(oi)
        headers["x-amz-storage-class"] = oi.internal.get(tx.META_TIER, "")
        if rng is None:
            body, status = data, 200
        else:
            body, status = data[rng[0]:rng[1] + 1], 206
            headers["Content-Range"] = \
                f"bytes {rng[0]}-{rng[1]}/{len(data)}"
        self.send_response(status)
        for k, v in headers.items():
            if v:
                self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        self._notify("s3:ObjectAccessed:Get", oi)

    def restore_object(self, ak):
        """POST ?restore (reference PostRestoreObjectHandler): bring a
        transitioned object's bytes back locally for Days days."""
        self._authorize(ak, "s3:RestoreObject")
        days = 1
        body = self._read_body()
        if body.strip():
            try:
                root = ET.fromstring(body)
                from ..bucket.objectlock import findtext
                days = int(findtext(root, "Days") or "1")
            except ET.ParseError as e:
                return self._error("MalformedXML", str(e), 400)
        oi = self.s3.obj.get_object_info(self.bucket, self.key,
                                         self._opts())
        from ..bucket import transition as tx
        if not tx.is_transitioned(oi):
            return self._error("InvalidObjectState",
                               "object is not archived", 403)
        if oi.size > 0 and tx.is_restored(oi):
            # already restored: just extend the expiry, no tier fetch
            self.s3.transition.extend_restore(self.bucket, oi, days)
        else:
            self.s3.transition.restore(self.bucket, oi, days)
        self._send(202)

    def head_object(self, ak):
        self._authorize(ak, "s3:GetObject")
        oi = self.s3.obj.get_object_info(self.bucket, self.key, self._opts())
        self._check_preconditions(oi)
        from ..bucket import transition as tx
        if tx.is_transitioned(oi):
            h = self._obj_headers(oi)
            h["Content-Length"] = str(tx.transitioned_size(oi))
            h["x-amz-storage-class"] = oi.internal.get(tx.META_TIER, "")
            if oi.size > 0 and tx.is_restored(oi):
                h["x-amz-restore"] = 'ongoing-request="false"'
            self.send_response(200)
            for k, v in h.items():
                if v:
                    self.send_header(k, v)
            self.end_headers()
            return
        sse = self._sse_read_ctx(oi)
        h = self._obj_headers(oi)
        if sse:
            h.update(sse[3])
            h["Content-Length"] = str(sse[2])
        else:
            from ..utils import compress as cz
            h["Content-Length"] = str(
                oi.actual_size if oi.internal.get(cz.META_COMPRESSION)
                else oi.size)
        self.send_response(200)
        for k, v in h.items():
            if v:
                self.send_header(k, v)
        self.end_headers()

    def _check_preconditions(self, oi):
        inm = self.hdr.get("if-none-match", "")
        if inm and inm.strip('"') == oi.etag:
            raise dt.NotModified(self.bucket, self.key)
        im = self.hdr.get("if-match", "")
        if im and im.strip('"') != oi.etag:
            raise dt.PreconditionFailed(self.bucket, self.key)

    # --- object lock / retention / legal hold -------------------------------

    def put_object_lock_config(self, ak):
        self._authorize(ak, "s3:PutBucketObjectLockConfiguration")
        from ..bucket import objectlock as ol
        meta = self.s3.bucket_meta.get(self.bucket)
        if not meta.object_lock_enabled:
            raise dt.InvalidRequest(
                self.bucket, "",
                "object lock is not enabled on this bucket")
        body = self._read_body()
        try:
            ol.parse_lock_config(body)
        except (ET.ParseError, ValueError) as e:
            return self._error("MalformedXML", str(e), 400)
        self.s3.bucket_meta.update(self.bucket, object_lock_xml=body)
        self._send(200)

    def get_object_lock_config(self, ak):
        self._authorize(ak, "s3:GetBucketObjectLockConfiguration")
        from ..bucket import objectlock as ol
        meta = self.s3.bucket_meta.get(self.bucket)
        if not meta.object_lock_enabled:
            return self._error("ObjectLockConfigurationNotFoundError",
                               "object lock is not enabled", 404)
        dr = ol.DefaultRetention()
        if meta.object_lock_xml:
            dr = ol.parse_lock_config(meta.object_lock_xml)
        self._send(200, ol.lock_config_xml(True, dr))

    def _lock_ctx(self):
        from ..bucket import objectlock as ol
        meta = self.s3.bucket_meta.get(self.bucket)
        default = ol.DefaultRetention()
        if meta.object_lock_enabled and meta.object_lock_xml:
            try:
                default = ol.parse_lock_config(meta.object_lock_xml)
            except ValueError:
                pass
        return meta.object_lock_enabled, default

    def put_object_retention(self, ak):
        self._authorize(ak, "s3:PutObjectRetention")
        from ..bucket import objectlock as ol
        enabled, _ = self._lock_ctx()
        if not enabled:
            raise dt.InvalidRequest(self.bucket, self.key,
                                    "bucket has no object lock")
        try:
            root = ET.fromstring(self._read_body())
        except ET.ParseError as e:
            return self._error("MalformedXML", str(e), 400)
        mode = ol.findtext(root, "Mode").upper()
        until = ol.findtext(root, "RetainUntilDate")
        if mode not in (ol.GOVERNANCE, ol.COMPLIANCE) or not until:
            raise dt.InvalidRequest(self.bucket, self.key,
                                    "invalid retention")
        try:
            until_t = ol.parse_iso8601(until)
        except ValueError:
            raise dt.InvalidRequest(self.bucket, self.key,
                                    "invalid retain-until date") from None
        opts = self._opts()
        oi = self.s3.obj.get_object_info(self.bucket, self.key, opts)
        cur = ol.retention_of({**oi.user_defined})
        bypass = self.hdr.get(
            "x-amz-bypass-governance-retention", "") == "true"
        if bypass:
            # weakening GOVERNANCE retention needs its own permission,
            # same as the delete path
            self._authorize(ak, "s3:BypassGovernanceRetention")
        cur_t = 0.0
        if cur.active:
            try:
                cur_t = ol.parse_iso8601(cur.retain_until)
            except ValueError:
                cur_t = 0.0
        if cur.active and cur.mode == ol.COMPLIANCE:
            # COMPLIANCE can only be extended, never weakened
            if mode != ol.COMPLIANCE or until_t < cur_t:
                raise dt.ObjectLocked(self.bucket, self.key,
                                      "COMPLIANCE retention active")
        elif cur.active and cur.mode == ol.GOVERNANCE and not bypass:
            if until_t < cur_t:
                raise dt.ObjectLocked(self.bucket, self.key,
                                      "GOVERNANCE retention active")
        self._mutate_lock_meta(opts, {ol.META_MODE: mode,
                                      ol.META_RETAIN_UNTIL: until})
        self._send(200)

    def get_object_retention(self, ak):
        self._authorize(ak, "s3:GetObjectRetention")
        from ..bucket import objectlock as ol
        oi = self.s3.obj.get_object_info(self.bucket, self.key, self._opts())
        ret = ol.retention_of(oi.user_defined)
        if not ret.mode:
            return self._error("NoSuchObjectLockConfiguration",
                               "no retention on this object", 404)
        self._send(200, (f"<Retention><Mode>{ret.mode}</Mode>"
                         f"<RetainUntilDate>{ret.retain_until}"
                         f"</RetainUntilDate></Retention>").encode())

    def put_object_legal_hold(self, ak):
        self._authorize(ak, "s3:PutObjectLegalHold")
        from ..bucket import objectlock as ol
        enabled, _ = self._lock_ctx()
        if not enabled:
            raise dt.InvalidRequest(self.bucket, self.key,
                                    "bucket has no object lock")
        try:
            root = ET.fromstring(self._read_body())
        except ET.ParseError as e:
            return self._error("MalformedXML", str(e), 400)
        status = ol.findtext(root, "Status").upper()
        if status not in ("ON", "OFF"):
            raise dt.InvalidRequest(self.bucket, self.key,
                                    "invalid legal hold status")
        self._mutate_lock_meta(self._opts(), {ol.META_LEGAL_HOLD: status})
        self._send(200)

    def get_object_legal_hold(self, ak):
        self._authorize(ak, "s3:GetObjectLegalHold")
        from ..bucket import objectlock as ol
        oi = self.s3.obj.get_object_info(self.bucket, self.key, self._opts())
        status = ol.legal_hold_of(oi.user_defined)
        self._send(200,
                   f"<LegalHold><Status>{status}</Status></LegalHold>"
                   .encode())

    def _mutate_lock_meta(self, opts, updates: dict):
        """Merge object-lock keys into the version's metadata in place
        (the reference rewrites xl.meta the same way for retention)."""
        self.s3.obj.update_object_meta(self.bucket, self.key, updates, opts)

    def _check_quota(self, incoming: int):
        """Hard bucket quota from the data-usage snapshot
        (cmd/bucket-quota.go enforceBucketQuotaHard): best-effort like the
        reference — usage trails the scanner's last sweep. The snapshot is
        cached on the server with a short TTL so the hot write path
        doesn't re-read+parse the usage blob per request."""
        import time as _t
        meta = self.s3.bucket_meta.get(self.bucket)
        if meta.quota <= 0:
            return
        cached = getattr(self.s3, "_usage_cache", None)
        if cached is None or _t.monotonic() - cached[0] > 10.0:
            from ..scanner import usage as usage_mod
            cached = (_t.monotonic(), usage_mod.load_usage(self.s3.obj))
            self.s3._usage_cache = cached
        usage = cached[1]
        used = usage.get("buckets", {}).get(self.bucket, {}).get("size", 0)
        if used + max(incoming, 0) > meta.quota:
            raise dt.QuotaExceeded(
                self.bucket, self.key,
                f"quota {meta.quota} would be exceeded")

    def _check_delete_lock(self, opts, key: str | None = None):
        """WORM enforcement for version deletes (a versionless delete only
        writes a delete marker, which object lock permits)."""
        if not opts.version_id:
            return
        from ..bucket import objectlock as ol
        meta = self.s3.bucket_meta.get(self.bucket)
        if not meta.object_lock_enabled:
            return
        key = self.key if key is None else key
        try:
            oi = self.s3.obj.get_object_info(self.bucket, key, opts)
        except dt.ObjectAPIError:
            return  # nothing to protect
        bypass = self.hdr.get(
            "x-amz-bypass-governance-retention", "") == "true"
        if bypass:
            # bypass needs its own permission
            self._authorize(self._last_ak,
                            "s3:BypassGovernanceRetention", self.bucket,
                            key)
        ol.check_delete_allowed(oi.user_defined, self.bucket, key, bypass)

    def delete_object(self, ak):
        self._authorize(ak, "s3:DeleteObject")
        self._last_ak = ak
        opts = self._opts()
        self._check_delete_lock(opts)
        oi = self.s3.obj.delete_object(self.bucket, self.key, opts)
        self._send(204, headers={
            "x-amz-version-id": oi.version_id or None,
            "x-amz-delete-marker": "true" if oi.delete_marker else None})
        self._notify("s3:ObjectRemoved:Delete", oi)

    def copy_object(self, ak):
        self._authorize(ak, "s3:PutObject")
        src = urllib.parse.unquote(self.hdr["x-amz-copy-source"])
        src_vid = ""
        if "?versionId=" in src:
            src, _, src_vid = src.partition("?versionId=")
        src = src.lstrip("/")
        src_bucket, _, src_key = src.partition("/")
        # the caller must be allowed to READ the source, not just write the
        # destination (otherwise copy exfiltrates unreadable objects)
        self._authorize(ak, "s3:GetObject", src_bucket, src_key)
        src_opts = ObjectOptions(version_id=src_vid)
        # SSE copy (decrypt source / re-encrypt destination) is not wired
        # yet; refuse clearly instead of copying ciphertext as plaintext
        from ..crypto.sse import META_SCHEME
        si_probe = self.s3.obj.get_object_info(src_bucket, src_key, src_opts)
        if si_probe.internal.get(META_SCHEME) or \
                self.hdr.get("x-amz-server-side-encryption") or \
                self.hdr.get(
                    "x-amz-server-side-encryption-customer-algorithm"):
            raise dt.NotImplemented(self.bucket, self.key)
        self._check_quota(si_probe.size)  # destination bucket quota
        dst_opts = self._opts()
        # object lock applies to the new version exactly like a PUT:
        # request headers validated, else the bucket default
        from ..bucket import objectlock as olock
        lock_enabled, lock_default = self._lock_ctx()
        lock_meta = olock.check_put_headers(
            self.hdr, self.bucket, self.key, lock_enabled, lock_default)
        directive = self.hdr.get("x-amz-metadata-directive", "COPY")
        if directive == "REPLACE":
            dst_opts.user_defined = self._user_meta()
            dst_opts.metadata_replace = True
        else:
            dst_opts.user_defined = dict(si_probe.user_defined)
            if si_probe.content_type:
                dst_opts.user_defined["content-type"] = si_probe.content_type
        # the copy moves the STORED bytes, so the compression markers must
        # travel with them or the destination would serve raw deflate
        from ..utils import compress as cz
        for k in (cz.META_COMPRESSION, cz.META_ACTUAL_SIZE):
            if k in si_probe.internal:
                dst_opts.user_defined[k] = si_probe.internal[k]
        dst_opts.user_defined.update(lock_meta)
        oi = self.s3.obj.copy_object(src_bucket, src_key, self.bucket,
                                     self.key, None, src_opts, dst_opts)
        self._send(200, xu.copy_object_xml(oi.etag, oi.mod_time),
                   headers={"x-amz-version-id": oi.version_id or None})
        self._notify("s3:ObjectCreated:Copy", oi)

    # --- object tagging -----------------------------------------------------

    def put_object_tagging(self, ak):
        self._authorize(ak, "s3:PutObjectTagging")
        tags = xu.parse_tagging(self._read_body())
        self.s3.obj.put_object_tags(self.bucket, self.key,
                                    urllib.parse.urlencode(tags),
                                    self._opts())
        self._send(200)

    def get_object_tagging(self, ak):
        self._authorize(ak, "s3:GetObjectTagging")
        enc = self.s3.obj.get_object_tags(self.bucket, self.key,
                                          self._opts())
        self._send(200, xu.tagging_xml(dict(urllib.parse.parse_qsl(enc))))

    def delete_object_tagging(self, ak):
        self._authorize(ak, "s3:PutObjectTagging")
        self.s3.obj.delete_object_tags(self.bucket, self.key, self._opts())
        self._send(204)

    # --- multipart ----------------------------------------------------------

    def initiate_upload(self, ak):
        self._authorize(ak, "s3:PutObject")
        if self.hdr.get("x-amz-server-side-encryption") or self.hdr.get(
                "x-amz-server-side-encryption-customer-algorithm"):
            # multipart SSE (per-part cipher streams) is not wired yet;
            # refuse instead of storing parts unencrypted
            raise dt.NotImplemented(self.bucket, self.key)
        opts = self._opts()
        opts.user_defined = self._user_meta()
        uid = self.s3.obj.new_multipart_upload(self.bucket, self.key, opts)
        self._send(200, xu.initiate_multipart_xml(self.bucket, self.key, uid))

    def put_part(self, ak):
        self._authorize(ak, "s3:PutObject")
        part_id = int(self.q("partNumber"))
        uid = self.q("uploadId")
        size = int(self.hdr.get("content-length", "-1") or "-1")
        if self.hdr.get("x-amz-content-sha256", "") == STREAMING_PAYLOAD:
            size = int(self.hdr.get("x-amz-decoded-content-length",
                                    str(size)))
        if size < 0:
            return self._error("MissingContentLength",
                               "Content-Length required", 411)
        self._check_quota(size)  # quota applies to multipart traffic too
        # Verify Content-MD5 / x-amz-content-sha256 on part bodies exactly
        # like PutObject — otherwise corrupted parts are accepted and only
        # surface as a confusing InvalidPart at complete time.
        hr = self._hash_reader(size)
        pi = self.s3.obj.put_object_part(self.bucket, self.key, uid,
                                         part_id, hr, size)
        self._send(200, headers={"ETag": f'"{pi.etag}"'})

    def list_parts(self, ak):
        self._authorize(ak, "s3:ListMultipartUploadParts")
        info = self.s3.obj.list_object_parts(
            self.bucket, self.key, self.q("uploadId"),
            int(self.q("part-number-marker", "0") or "0"),
            min(int(self.q("max-parts", "1000") or "1000"), 10_000))
        self._send(200, xu.list_parts_xml(info))

    def list_uploads(self, ak):
        self._authorize(ak, "s3:ListBucketMultipartUploads")
        self.s3.obj.get_bucket_info(self.bucket)
        prefix = self.q("prefix")
        max_uploads = min(int(self.q("max-uploads", "1000") or "1000"),
                          10_000)
        info = self.s3.obj.list_multipart_uploads(self.bucket, prefix,
                                                  max_uploads)
        self._send(200, xu.list_uploads_xml(self.bucket, prefix, max_uploads,
                                            info))

    def abort_upload(self, ak):
        self._authorize(ak, "s3:AbortMultipartUpload")
        self.s3.obj.abort_multipart_upload(self.bucket, self.key,
                                           self.q("uploadId"))
        self._send(204)

    def complete_upload(self, ak):
        self._authorize(ak, "s3:PutObject")
        parts = xu.parse_complete_multipart(self._read_body())
        opts = self._opts()
        oi = self.s3.obj.complete_multipart_upload(
            self.bucket, self.key, self.q("uploadId"), parts, opts)
        # multipart-complete is a replication charge point too; the
        # status rides a meta update since the parts were written long
        # before the obligation existed
        rs = getattr(self.s3, "replication_sys", None)
        if rs is not None and rs.heads_up(self.bucket, self.key) is not None:
            from ..bucket import replicate as repl
            try:
                self.s3.obj.update_object_meta(
                    self.bucket, self.key,
                    {repl.META_REP_STATUS: repl.PENDING})
            except Exception:  # noqa: BLE001 — charge still queues
                pass
        self._send(200, xu.complete_multipart_xml(
            f"{self.s3.endpoint()}/{self.bucket}/{self.key}",
            self.bucket, self.key, oi.etag),
            headers={"x-amz-version-id": oi.version_id or None})
        self._notify("s3:ObjectCreated:CompleteMultipartUpload", oi)


class _LenReader:
    """File-like with a known length: lets requests stream a proxied
    body at constant memory while still sending Content-Length."""

    def __init__(self, stream, size: int):
        self.stream = stream
        self._size = size

    def read(self, n: int = -1) -> bytes:
        return self.stream.read(n)

    def __len__(self):
        return self._size


class _CappedReader:
    """Bound a socket read to the declared Content-Length (socket streams
    never EOF on keep-alive connections); reports consumption back to the
    handler for end-of-request draining."""

    def __init__(self, raw, size: int, handler=None):
        self.raw = raw
        self.remaining = max(0, size) if size >= 0 else -1
        self.handler = handler

    def read(self, n: int = -1) -> bytes:
        if self.remaining == 0:
            return b""
        if self.remaining > 0:
            n = self.remaining if n < 0 else min(n, self.remaining)
        b = self.raw.read(n)
        if self.remaining > 0:
            self.remaining -= len(b)
        if self.handler is not None:
            self.handler._consumed += len(b)
        return b

    def readinto(self, view) -> int:
        """Zero-copy leg of the PUT ingest: the erasure pipeline's pooled
        block buffers reach the socket's BufferedReader directly, so body
        bytes are never materialized as per-block ``bytes`` objects."""
        if self.remaining == 0:
            return 0
        view = memoryview(view).cast("B")
        if 0 < self.remaining < len(view):
            view = view[: self.remaining]
        got = self.raw.readinto(view)
        got = got or 0
        if self.remaining > 0:
            self.remaining -= got
        if self.handler is not None:
            self.handler._consumed += got
        return got
