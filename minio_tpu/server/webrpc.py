"""Web console plane: JSON-RPC 2.0 endpoint + upload/download routes
(reference cmd/web-handlers.go, 2,445 LoC, and cmd/web-router.go: the
browser UI's backend — Login issues a JWT, the webrpc methods mirror a
subset of the S3 surface for the console, and /minio/upload|download
move object data with the JWT as credential).

Methods (reference web.* names): Login, ServerInfo, StorageInfo,
MakeBucket, DeleteBucket, ListBuckets, ListObjects, RemoveObject,
SetAuth, CreateURLToken, PresignedGet. The JWT is HMAC-SHA256 over
header.payload (the reference signs HS512 with the credential secret;
same construction, one algorithm)."""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time

from ..objectlayer import datatypes as dt

TOKEN_TTL_S = 24 * 3600
URL_TOKEN_TTL_S = 60


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def _b64url_dec(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def make_jwt(access_key: str, secret: str, ttl_s: int = TOKEN_TTL_S) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = _b64url(json.dumps({
        "sub": access_key, "iss": "web",
        "exp": int(time.time()) + ttl_s}).encode())
    msg = f"{header}.{claims}".encode()
    sig = _b64url(hmac.new(secret.encode(), msg, hashlib.sha256).digest())
    return f"{header}.{claims}.{sig}"


def check_jwt(token: str, lookup_secret) -> str:
    """Validate signature + expiry; returns the access key or ''."""
    try:
        header, claims, sig = token.split(".")
        payload = json.loads(_b64url_dec(claims))
        ak = payload.get("sub", "")
        secret = lookup_secret(ak)
        if not secret:
            return ""
        msg = f"{header}.{claims}".encode()
        want = _b64url(hmac.new(secret.encode(), msg,
                                hashlib.sha256).digest())
        if not hmac.compare_digest(want, sig):
            return ""
        if payload.get("exp", 0) < time.time():
            return ""
        return ak
    except (ValueError, AttributeError):
        return ""


def _auth(h, params: dict) -> str:
    """JWT from the Authorization header or rpc params; returns access
    key or '' (reference isAuthTokenValid)."""
    token = ""
    auth = h.hdr.get("authorization", "")
    if auth.startswith("Bearer "):
        token = auth[7:]
    token = params.get("token", token)
    return check_jwt(token, h.s3.lookup_secret)


def _check(h, ak: str, action: str, bucket: str = "", obj: str = ""):
    """Run the same policy gate the S3 path uses: a scoped IAM user's
    JWT must not grant more through the console than through S3
    (reference web-handlers.go checks each action the same way)."""
    gate = getattr(h.s3, "authorize", None)
    if gate is None:
        return  # single-credential server: any valid JWT is root
    if not gate(ak, action, bucket, obj):
        raise dt.AccessDenied(bucket, obj, extra=f"not allowed {action}")


def handle_webrpc(h) -> None:
    """POST /minio/webrpc — JSON-RPC 2.0 (one call per request, like the
    reference's gorilla/rpc v2 JSON codec)."""
    if h.command != "POST":
        return h._error("MethodNotAllowed", "webrpc is POST-only", 405)
    try:
        req = json.loads(h._read_body() or b"{}")
        if not isinstance(req, dict):
            raise ValueError("request must be a JSON object")
        method = req.get("method", "")
        params = req.get("params") or {}
        if isinstance(params, list):
            params = params[0] if params else {}
        if not isinstance(params, dict):
            raise ValueError("params must be an object")
        rpc_id = req.get("id", 1)
    except ValueError as e:
        return _reply(h, 1, error=f"parse error: {e}")
    name = method.split(".", 1)[-1].lower()
    fn = _METHODS.get(name)
    if fn is None:
        return _reply(h, rpc_id, error=f"unknown method {method}")
    ak = ""
    if name not in _NO_AUTH:
        ak = _auth(h, params)
        if not ak:
            return _reply(h, rpc_id, error="authentication failed",
                          code=401)
    try:
        return _reply(h, rpc_id, result=fn(h, params, ak))
    except dt.ObjectAPIError as e:
        return _reply(h, rpc_id, error=str(e))
    except Exception as e:  # noqa: BLE001
        return _reply(h, rpc_id, error=f"internal error: {e}")


def _reply(h, rpc_id, result=None, error=None, code: int = 200):
    body: dict = {"jsonrpc": "2.0", "id": rpc_id}
    if error is not None:
        body["error"] = {"message": error}
    else:
        body["result"] = result
    h._send(code, json.dumps(body).encode(), "application/json")


# -- methods ------------------------------------------------------------------


def _m_login(h, p: dict, ak: str):
    user = p.get("username", "")
    sk = h.s3.lookup_secret(user)
    if not sk or not hmac.compare_digest(
            sk.encode(), str(p.get("password", "")).encode()):
        raise dt.AccessDenied(extra="invalid credentials")
    return {"token": make_jwt(user, sk), "uiVersion": "minio-tpu"}


def _m_server_info(h, p: dict, ak: str):
    import platform
    return {"MinioVersion": "minio-tpu/0.1",
            "MinioPlatform": platform.platform(),
            "MinioRuntime": platform.python_version(),
            "MinioRegion": h.s3.region}


def _m_storage_info(h, p: dict, ak: str):
    return h.s3.obj.storage_info()


def _m_make_bucket(h, p: dict, ak: str):
    bucket = p.get("bucketName", "")
    _check(h, ak, "s3:CreateBucket", bucket)
    # same core as the S3 path: metadata record, federation namespace
    # check + DNS registration
    h.s3.create_bucket(bucket)
    return True


def _m_delete_bucket(h, p: dict, ak: str):
    bucket = p.get("bucketName", "")
    _check(h, ak, "s3:DeleteBucket", bucket)
    h.s3.remove_bucket(bucket)
    return True


def _m_list_buckets(h, p: dict, ak: str):
    _check(h, ak, "s3:ListAllMyBuckets")
    return {"buckets": [{"name": b.name, "creationDate": b.created}
                        for b in h.s3.obj.list_buckets()]}


def _m_list_objects(h, p: dict, ak: str):
    bucket = p.get("bucketName", "")
    prefix = p.get("prefix", "")
    _check(h, ak, "s3:ListBucket", bucket)
    res = h.s3.obj.list_objects(bucket, prefix=prefix, delimiter="/",
                                max_keys=1000,
                                marker=p.get("marker", ""))
    return {"objects": [
        {"name": oi.name, "size": oi.size, "lastModified": oi.mod_time,
         "contentType": oi.content_type, "etag": oi.etag}
        for oi in res.objects],
        "prefixes": list(res.prefixes),
        "istruncated": res.is_truncated,
        "nextmarker": res.next_marker}


def _m_remove_object(h, p: dict, ak: str):
    bucket = p.get("bucketName", "")
    for obj in p.get("objects", []) or [p.get("objectName", "")]:
        if obj:
            _check(h, ak, "s3:DeleteObject", bucket, obj)
            h.s3.obj.delete_object(bucket, obj)
    return True


def _m_set_auth(h, p: dict, ak: str):
    # the reference rotates root credentials; here credentials live in
    # IAM/env, so guide the operator there instead of silently no-oping
    raise dt.NotImplemented(
        extra="use the admin IAM API to manage credentials")


def _m_create_url_token(h, p: dict, ak: str):
    """Short-lived token for download links (reference CreateURLToken)."""
    return {"token": make_jwt(ak, h.s3.lookup_secret(ak),
                              ttl_s=URL_TOKEN_TTL_S)}


_BUCKET_ARN = "arn:aws:s3:::{b}"
_OBJ_ARN = "arn:aws:s3:::{b}/{p}*"
_WRITE_OBJ_ACTIONS = ["s3:AbortMultipartUpload", "s3:DeleteObject",
                      "s3:ListMultipartUploadParts", "s3:PutObject"]


def _policy_doc(h, bucket: str) -> dict:
    meta = h.s3.bucket_meta.get(bucket)
    if meta.policy_json:
        try:
            return json.loads(meta.policy_json)
        except ValueError:
            pass
    return {"Version": "2012-10-17", "Statement": []}


def _stmt_objects(stmt) -> list[str]:
    res = stmt.get("Resource", [])
    return [res] if isinstance(res, str) else list(res)


def _is_anon(stmt) -> bool:
    pr = stmt.get("Principal")
    aws = pr.get("AWS") if isinstance(pr, dict) else pr
    vals = [aws] if isinstance(aws, str) else (aws or [])
    return stmt.get("Effect") == "Allow" and "*" in vals


def _prefix_level(doc: dict, bucket: str, prefix: str) -> str:
    obj_arn = _OBJ_ARN.format(b=bucket, p=prefix)
    read = write = False
    for stmt in doc.get("Statement", []):
        if not _is_anon(stmt) or obj_arn not in _stmt_objects(stmt):
            continue
        acts = stmt.get("Action", [])
        acts = [acts] if isinstance(acts, str) else acts
        if "s3:GetObject" in acts:
            read = True
        if "s3:PutObject" in acts:
            write = True
    return {(False, False): "none", (True, False): "readonly",
            (False, True): "writeonly", (True, True): "readwrite"}[
        (read, write)]


def _m_get_bucket_policy(h, p: dict, ak: str):
    """The canned anonymous-access level at a prefix (reference
    web-handlers.go:1786 via minio-go policy.GetPolicy)."""
    bucket = p.get("bucketName", "")
    _check(h, ak, "s3:GetBucketPolicy", bucket)
    h.s3.obj.get_bucket_info(bucket)
    doc = _policy_doc(h, bucket)
    return {"policy": _prefix_level(doc, bucket, p.get("prefix", ""))}


def _m_list_all_bucket_policies(h, p: dict, ak: str):
    """Every prefix with a canned anonymous policy (reference
    web-handlers.go:1884)."""
    bucket = p.get("bucketName", "")
    _check(h, ak, "s3:GetBucketPolicy", bucket)
    h.s3.obj.get_bucket_info(bucket)
    doc = _policy_doc(h, bucket)
    head = f"arn:aws:s3:::{bucket}/"
    prefixes = set()
    for stmt in doc.get("Statement", []):
        if not _is_anon(stmt):
            continue
        for arn in _stmt_objects(stmt):
            if arn.startswith(head) and arn.endswith("*"):
                prefixes.add(arn[len(head):-1])
    return {"policies": [
        {"prefix": pre + "*",
         "policy": _prefix_level(doc, bucket, pre)}
        for pre in sorted(prefixes)]}


def _m_set_bucket_policy(h, p: dict, ak: str):
    """Set/replace the canned anonymous policy at a prefix (reference
    web-handlers.go:1973): none|readonly|writeonly|readwrite become the
    standard AWS statement shapes, which the S3 anonymous-access gate
    then enforces."""
    bucket = p.get("bucketName", "")
    prefix = p.get("prefix", "")
    level = p.get("policy", "none")
    if level not in ("none", "readonly", "writeonly", "readwrite"):
        raise dt.InvalidRequest(bucket, "", f"bad policy {level!r}")
    _check(h, ak, "s3:PutBucketPolicy", bucket)
    h.s3.obj.get_bucket_info(bucket)
    doc = _policy_doc(h, bucket)
    bucket_arn = _BUCKET_ARN.format(b=bucket)
    obj_arn = _OBJ_ARN.format(b=bucket, p=prefix)
    # strip this prefix's statements (object-level, and bucket-level
    # ListBucket entries conditioned on the prefix)
    kept = []
    for stmt in doc.get("Statement", []):
        if _is_anon(stmt):
            if _stmt_objects(stmt) == [obj_arn]:
                continue
            cond = stmt.get("Condition", {}).get(
                "StringEquals", {}).get("s3:prefix", [])
            if cond == [prefix]:
                continue
        kept.append(stmt)
    if level in ("readonly", "readwrite"):
        kept.append({"Effect": "Allow", "Principal": {"AWS": ["*"]},
                     "Action": ["s3:ListBucket"],
                     "Condition": {"StringEquals": {"s3:prefix": [prefix]}},
                     "Resource": [bucket_arn]})
        kept.append({"Effect": "Allow", "Principal": {"AWS": ["*"]},
                     "Action": ["s3:GetObject"], "Resource": [obj_arn]})
    if level in ("writeonly", "readwrite"):
        kept.append({"Effect": "Allow", "Principal": {"AWS": ["*"]},
                     "Action": list(_WRITE_OBJ_ACTIONS),
                     "Resource": [obj_arn]})
    doc["Statement"] = kept
    h.s3.bucket_meta.update(
        bucket, policy_json=json.dumps(doc).encode() if kept else b"")
    return True


def _m_get_discovery_doc(h, p: dict, ak: str):
    """OpenID discovery for console SSO (reference GetDiscoveryDoc,
    web-handlers.go:2223): the configured provider's document, or null
    when SSO is not configured. Unauthenticated by design — the login
    page needs it before any credential exists."""
    iam = h.s3.iam
    prov = iam._openid_provider() if iam is not None else None
    if prov is None or not prov.configured():
        return {"DiscoveryDoc": None}
    doc = {}
    try:
        doc = prov.discovery_doc()
    except Exception:  # noqa: BLE001 — IDP down: login page degrades
        pass
    return {"DiscoveryDoc": doc or None}


def _m_login_sts(h, p: dict, ak: str):
    """Console SSO login (reference LoginSTS, web-handlers.go:2240):
    exchange an OpenID id_token for STS temporary credentials, return a
    web JWT bound to them."""
    if h.s3.iam is None:
        raise dt.NotImplemented(extra="STS login needs IAM enabled")
    try:
        cred = h.s3.iam.assume_role_with_web_identity(
            p.get("token", ""), 3600, b"")
    except ValueError as e:
        raise dt.AccessDenied(extra=f"STS login failed: {e}") from None
    return {"token": make_jwt(cred.access_key, cred.secret_key),
            "uiVersion": "minio-tpu"}


def _m_presigned_get(h, p: dict, ak: str):
    """Presigned GET URL for the console's share dialog."""
    from .auth import presign_v4
    bucket, obj = p.get("bucket", ""), p.get("object", "")
    _check(h, ak, "s3:GetObject", bucket, obj)
    expiry = min(int(p.get("expiry", 3600) or 3600), 7 * 24 * 3600)
    scheme = "https" if getattr(h.s3, "tls", False) else "http"
    url = presign_v4(
        "GET", scheme, h.hdr.get("host", ""), f"/{bucket}/{obj}",
        ak, h.s3.lookup_secret(ak), h.s3.region, expiry)
    return {"url": url}


_METHODS = {
    "login": _m_login,
    "serverinfo": _m_server_info,
    "storageinfo": _m_storage_info,
    "makebucket": _m_make_bucket,
    "deletebucket": _m_delete_bucket,
    "listbuckets": _m_list_buckets,
    "listobjects": _m_list_objects,
    "removeobject": _m_remove_object,
    "setauth": _m_set_auth,
    "createurltoken": _m_create_url_token,
    "presignedget": _m_presigned_get,
    "getbucketpolicy": _m_get_bucket_policy,
    "listallbucketpolicies": _m_list_all_bucket_policies,
    "setbucketpolicy": _m_set_bucket_policy,
    "getdiscoverydoc": _m_get_discovery_doc,
    "loginsts": _m_login_sts,
}

#: methods callable without a JWT: Login issues tokens, LoginSTS trades
#: an IDP token for one, and the login page needs the discovery doc
#: before any credential exists (reference web-router registers these
#: the same way)
_NO_AUTH = {"login", "loginsts", "getdiscoverydoc"}


# -- static console -----------------------------------------------------------


_CONSOLE_CACHE: bytes | None = None


def handle_console(h) -> None:
    """GET /minio/ — the embedded single-file console SPA (reference
    cmd/web-router.go:1 serves the compiled browser/ React app from an
    in-binary asset FS; here the app is one static HTML file beside this
    module, no build step)."""
    global _CONSOLE_CACHE
    if h.command != "GET":
        return h._error("MethodNotAllowed", "console is GET-only", 405)
    if _CONSOLE_CACHE is None:
        import os
        path = os.path.join(os.path.dirname(__file__), "console.html")
        with open(path, "rb") as f:
            _CONSOLE_CACHE = f.read()
    h._send(200, _CONSOLE_CACHE, "text/html; charset=utf-8")


# -- upload / download routes -------------------------------------------------


def handle_upload(h, bucket: str, object: str) -> None:
    """PUT /minio/upload/<bucket>/<object> with Bearer JWT (reference
    web-handlers.go Upload; the router binds it to PUT only)."""
    if h.command != "PUT":
        return h._error("MethodNotAllowed", "upload is PUT-only", 405)
    ak = _auth(h, {})
    if not ak:
        return h._error("AccessDenied", "invalid token", 401)
    try:
        _check(h, ak, "s3:PutObject", bucket, object)
        size = int(h.hdr.get("content-length", "0") or "0")
        from ..utils.hashreader import HashReader
        # _body_stream bounds the socket read to Content-Length
        # (keep-alive sockets never EOF) and handles aws-chunked bodies
        hr = HashReader(h._body_stream(size), size)
        from ..utils.mimedb import content_type
        ct = h.hdr.get("content-type") or content_type(
            object, "application/octet-stream")
        oi = h.s3.obj.put_object(
            bucket, object, hr, size,
            dt.ObjectOptions(user_defined={"content-type": ct}))
    except dt.ObjectAPIError as e:
        return h._api_error(e)
    h._send(200, json.dumps({"etag": oi.etag}).encode(),
            "application/json")


def _disposition_name(object: str) -> str:
    """Filename for Content-Disposition: the key's last segment with
    header-breaking characters stripped (CR/LF would split the response;
    a double quote would escape the parameter)."""
    name = object.rsplit("/", 1)[-1]
    return "".join(c for c in name
                   if c not in '"\\\r\n' and ord(c) >= 0x20) or "download"


def handle_download(h, bucket: str, object: str) -> None:
    """GET /minio/download/<bucket>/<object>?token=... (reference
    web-handlers.go Download: the token rides the query string because
    browser downloads can't set headers)."""
    if h.command != "GET":
        return h._error("MethodNotAllowed", "download is GET-only", 405)
    q = {k: v[0] for k, v in h.query.items()}
    ak = check_jwt(q.get("token", ""), h.s3.lookup_secret)
    if not ak:
        return h._error("AccessDenied", "invalid token", 401)
    try:
        _check(h, ak, "s3:GetObject", bucket, object)
        oi = h.s3.obj.get_object_info(bucket, object)
        # same read context as the S3 GET path: decrypt SSE-S3/KMS with
        # the unsealed OEK, inflate compressed objects (SSE-C correctly
        # errors here — a browser download can't carry the customer key)
        h.bucket, h.key = bucket, object
        sse = h._sse_read_ctx(oi)
    except dt.ObjectAPIError as e:
        return h._api_error(e)
    plain_size = _logical_size(h, oi, sse)
    h.send_response(200)
    h.send_header("Content-Type",
                  oi.content_type or "application/octet-stream")
    h.send_header("Content-Length", str(plain_size))
    h.send_header("Content-Disposition",
                  f'attachment; filename="{_disposition_name(object)}"')
    h.end_headers()
    if plain_size > 0:
        _write_logical(h, bucket, object, oi, sse, h.wfile)


def _logical_size(h, oi, sse) -> int:
    from ..utils import compress as cz
    if sse:
        return sse[2]
    return oi.actual_size if oi.internal.get(cz.META_COMPRESSION) \
        else oi.size


def _write_logical(h, bucket: str, object: str, oi, sse, sink) -> None:
    """Stream the object's PLAINTEXT into sink — the same read context
    as the S3 GET path (decrypt SSE with the unsealed OEK, inflate
    compressed objects)."""
    from ..utils import compress as cz
    compressed = oi.internal.get(cz.META_COMPRESSION, "")
    if sse:
        from ..crypto import DecryptWriter
        oek, base_iv, psize, _, cipher = sse
        dw = DecryptWriter(sink, oek, base_iv, 0, 0, psize,
                           bucket, object, cipher=cipher)
        h.s3.obj.get_object(bucket, object, dw)
        dw.finish()
    elif compressed:
        dz = cz.decompress_writer(compressed, sink)
        h.s3.obj.get_object(bucket, object, dz)
        dz.finish()
    else:
        h.s3.obj.get_object(bucket, object, sink)


def handle_download_zip(h) -> None:
    """POST /minio/zip?token=... body {bucketName, prefix, objects: []}
    — the console's multi-select download (reference web-handlers.go
    DownloadZip): entries ending in "/" expand to every object under
    them; each entry streams through the logical read context.

    Every REQUESTED entry (object or folder prefix) is authorized
    up-front — so a read-denied caller gets a proper 403 before any
    prefix walk or data read happens — then the archive STREAMS chunked
    with entries resolved and re-authorized LAZILY: folder prefixes
    expand via iter_objects while streaming and each object's
    metadata/SSE context is fetched just before its bytes go out, so a
    multi-GB selection never pre-buffers O(#objects) ObjectInfo +
    unsealed-OEK tuples (the reference checks each requested entry
    before listing and streams the same way). A mid-stream denial or
    failure cuts the connection — with chunked framing the client sees
    a truncated archive, never a silent success."""
    import zipfile
    if h.command != "POST":
        return h._error("MethodNotAllowed", "zip is POST-only", 405)
    q = {k: v[0] for k, v in h.query.items()}
    ak = check_jwt(q.get("token", ""), h.s3.lookup_secret)
    if not ak:
        return h._error("AccessDenied", "invalid token", 401)
    try:
        req = json.loads(h._read_body() or b"{}")
        bucket = req.get("bucketName", "")
        prefix = req.get("prefix", "")
        names = req.get("objects") or []
        if not isinstance(bucket, str) or not bucket or \
                not isinstance(prefix, str) or \
                not isinstance(names, list) or not names or \
                not all(isinstance(n, str) for n in names):
            raise ValueError("bucketName and string objects[] required")
    except (ValueError, AttributeError) as e:
        return h._error("InvalidRequest", f"bad zip request: {e}", 400)
    try:
        # authorize every REQUESTED entry before any walk/read: folder
        # prefixes gate on the prefix itself (a deny on bucket/prefix/*
        # matches), explicit objects on their key — nothing is listed or
        # resolved for a caller the policy rejects. Explicitly named
        # objects also get a cheap existence probe so a typo answers a
        # proper pre-stream NoSuchKey (the result is discarded: no
        # ObjectInfo/OEK buffering; folder contents stay fully lazy).
        h.s3.obj.get_bucket_info(bucket)
        for name in names:
            full = prefix + name
            _check(h, ak, "s3:GetObject", bucket, full)
            if not full.endswith("/"):
                h.s3.obj.get_object_info(bucket, full)
    except dt.ObjectAPIError as e:
        return h._api_error(e)
    h.send_response(200)
    h.send_header("Content-Type", "application/zip")
    h.send_header("Transfer-Encoding", "chunked")
    h.send_header("Content-Disposition",
                  'attachment; filename="download.zip"')
    h.end_headers()
    from .s3api import _ChunkedWriter
    out = _ChunkedWriter(h.wfile)

    def keys():
        for name in names:
            full = prefix + name
            if full.endswith("/"):
                yield from (oi.name for oi in
                            h.s3.obj.iter_objects(bucket, full))
            else:
                yield full

    try:
        # ZipFile handles the non-seekable sink via data descriptors
        with zipfile.ZipFile(out, "w", zipfile.ZIP_STORED,
                             allowZip64=True) as zf:
            for key in keys():
                # PER-OBJECT authorization, like handle_download and the
                # reference: per-key Deny statements must hold inside a
                # multi-select zip too — re-checked lazily as each entry
                # streams, with metadata/SSE resolved just-in-time
                _check(h, ak, "s3:GetObject", bucket, key)
                oi = h.s3.obj.get_object_info(bucket, key)
                h.bucket, h.key = bucket, key
                sse = h._sse_read_ctx(oi)
                arc = key[len(prefix):] if key.startswith(prefix) else key
                with zf.open(zipfile.ZipInfo(arc or key), "w",
                             force_zip64=True) as entry:
                    if _logical_size(h, oi, sse) > 0:
                        _write_logical(h, bucket, key, oi, sse, entry)
    except Exception:  # noqa: BLE001 — mid-stream failure/denial: cut
        h.close_connection = True  # the connection, the client sees EOF
        return
    out.close()
