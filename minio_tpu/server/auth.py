"""AWS Signature V4 verification (reference cmd/signature-v4.go,
cmd/streaming-signature-v4.go, cmd/auth-handler.go): header-signed,
presigned-URL, UNSIGNED-PAYLOAD, and streaming aws-chunked payloads."""
from __future__ import annotations

import datetime
import hashlib
import hmac
import time
import urllib.parse
from dataclasses import dataclass

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
SIGN_V4_ALGO = "AWS4-HMAC-SHA256"
PRESIGN_EXPIRY_MAX = 7 * 24 * 3600


class AuthError(Exception):
    def __init__(self, code: str, message: str, status: int = 403):
        self.code = code
        self.message = message
        self.status = status
        super().__init__(f"{code}: {message}")


@dataclass
class Credentials:
    access_key: str
    secret_key: str

    def is_valid(self) -> bool:
        return len(self.access_key) >= 3 and len(self.secret_key) >= 8


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = "s3") -> bytes:
    k = _hmac(f"AWS4{secret}".encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query: dict[str, list[str]],
                    drop: tuple[str, ...] = ()) -> str:
    pairs = []
    for k in sorted(query):
        if k in drop:
            continue
        for v in sorted(query[k]):
            pairs.append(f"{uri_encode(k)}={uri_encode(v)}")
    return "&".join(pairs)


def canonical_request(method: str, path: str, query: dict[str, list[str]],
                      headers: dict[str, str], signed_headers: list[str],
                      payload_hash: str,
                      drop_query: tuple[str, ...] = ()) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    return "\n".join([
        method,
        uri_encode(path, encode_slash=False) or "/",
        canonical_query(query, drop_query),
        canon_headers,
        ";".join(signed_headers),
        payload_hash,
    ])


def string_to_sign(timestamp: str, scope: str, creq: str) -> str:
    return "\n".join([SIGN_V4_ALGO, timestamp, scope,
                      hashlib.sha256(creq.encode()).hexdigest()])


@dataclass
class ParsedSig:
    access_key: str
    scope_date: str
    region: str
    service: str
    signed_headers: list[str]
    signature: str


def parse_auth_header(value: str) -> ParsedSig:
    if not value.startswith(SIGN_V4_ALGO):
        raise AuthError("SignatureDoesNotMatch",
                        "unsupported signature algorithm")
    fields: dict[str, str] = {}
    for part in value[len(SIGN_V4_ALGO):].split(","):
        part = part.strip()
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        fields[k.strip()] = v.strip()
    try:
        cred = fields["Credential"].split("/")
        return ParsedSig(
            access_key="/".join(cred[:-4]),
            scope_date=cred[-4], region=cred[-3], service=cred[-2],
            signed_headers=fields["SignedHeaders"].split(";"),
            signature=fields["Signature"])
    except (KeyError, IndexError) as e:
        raise AuthError("AuthorizationHeaderMalformed",
                        f"malformed authorization header: {e}") from e


class SigV4Verifier:
    """Stateless request verifier bound to a credential lookup function
    (access_key -> secret or None) and a region."""

    def __init__(self, lookup, region: str = "us-east-1"):
        self.lookup = lookup
        self.region = region

    # -- header-signed -------------------------------------------------------

    def verify(self, method: str, path: str, query: dict[str, list[str]],
               headers: dict[str, str]) -> str:
        """Verify; returns the authenticated access key. Raises AuthError."""
        auth = headers.get("authorization", "")
        if auth.startswith("AWS "):
            return self._verify_v2(method, path, query, headers, auth)
        if auth:
            return self._verify_header(method, path, query, headers, auth)
        ci = dict_ci(query)
        if "X-Amz-Signature" in ci:
            return self._verify_presigned(method, path, query, headers)
        if "Signature" in ci and "AWSAccessKeyId" in ci:
            return self._verify_presigned_v2(method, path, query)
        raise AuthError("AccessDenied", "no authentication provided")

    # --- AWS Signature Version 2 (reference cmd/signature-v2.go) ------------

    _V2_SUBRESOURCES = (
        "acl", "delete", "lifecycle", "location", "logging", "notification",
        "partNumber", "policy", "requestPayment", "response-cache-control",
        "response-content-disposition", "response-content-encoding",
        "response-content-language", "response-content-type",
        "response-expires", "restore", "tagging", "torrent", "uploadId",
        "uploads", "versionId", "versioning", "versions", "website",
        "select", "select-type", "object-lock", "retention", "legal-hold",
    )

    def _v2_string_to_sign(self, method: str, path: str,
                           query: dict[str, list[str]],
                           headers: dict[str, str], expires: str = "") -> str:
        amz = sorted((k.lower().strip(), ",".join(v if isinstance(v, list)
                                                  else [v]))
                     for k, v in headers.items()
                     if k.lower().startswith("x-amz-"))
        canon_amz = "".join(f"{k}:{vs.strip()}\n" for k, vs in amz)
        sub = sorted(k for k in query if k in self._V2_SUBRESOURCES)
        resource = path
        if sub:
            parts = []
            for k in sub:
                v = query[k][0] if query[k] and query[k][0] else ""
                parts.append(f"{k}={v}" if v else k)
            resource += "?" + "&".join(parts)
        # spec: when x-amz-date is sent it rides CanonicalizedAmzHeaders
        # and the Date line is EMPTY (double-counting it rejects every
        # conforming client that can't set Date)
        if expires:
            date = expires
        elif headers.get("x-amz-date"):
            date = ""
        else:
            date = headers.get("date", "")
        return "\n".join([
            method,
            headers.get("content-md5", ""),
            headers.get("content-type", ""),
            date,
            canon_amz + resource,
        ])

    def _v2_signature(self, secret: str, sts: str) -> str:
        import base64
        return base64.b64encode(
            hmac.new(secret.encode(), sts.encode(),
                     hashlib.sha1).digest()).decode()

    def _verify_v2(self, method, path, query, headers, auth) -> str:
        try:
            ak, want = auth[len("AWS "):].split(":", 1)
        except ValueError:
            raise AuthError("InvalidArgument",
                            "malformed v2 authorization") from None
        secret = self.lookup(ak)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", "access key not found")
        sts = self._v2_string_to_sign(method, path, query, headers)
        if not hmac.compare_digest(self._v2_signature(secret, sts), want):
            raise AuthError("SignatureDoesNotMatch", "v2 signature mismatch")
        return ak

    def _verify_presigned_v2(self, method, path, query) -> str:
        ci = dict_ci(query)
        ak = first(ci, "AWSAccessKeyId")
        want = first(ci, "Signature")
        expires = first(ci, "Expires")
        secret = self.lookup(ak)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", "access key not found")
        try:
            if float(expires) < time.time():
                raise AuthError("AccessDenied", "presigned URL expired")
        except ValueError:
            raise AuthError("InvalidArgument", "bad Expires") from None
        q = {k: v for k, v in query.items()
             if k not in ("Signature", "AWSAccessKeyId", "Expires")}
        sts = self._v2_string_to_sign(method, path, q, {}, expires=expires)
        if not hmac.compare_digest(self._v2_signature(secret, sts), want):
            raise AuthError("SignatureDoesNotMatch", "v2 signature mismatch")
        return ak

    def _verify_header(self, method, path, query, headers, auth) -> str:
        sig = parse_auth_header(auth)
        secret = self.lookup(sig.access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", "access key not found")
        timestamp = headers.get("x-amz-date") or headers.get("date", "")
        if not timestamp:
            raise AuthError("AccessDenied", "missing date header")
        self._check_skew(timestamp)
        payload_hash = headers.get("x-amz-content-sha256",
                                   UNSIGNED_PAYLOAD)
        scope = f"{sig.scope_date}/{sig.region}/{sig.service}/aws4_request"
        creq = canonical_request(method, path, query, headers,
                                 sig.signed_headers, payload_hash)
        sts = string_to_sign(timestamp, scope, creq)
        key = signing_key(secret, sig.scope_date, sig.region, sig.service)
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig.signature):
            raise AuthError("SignatureDoesNotMatch",
                            "request signature mismatch")
        return sig.access_key

    # -- presigned URL -------------------------------------------------------

    def _verify_presigned(self, method, path, query, headers) -> str:
        q = dict_ci(query)
        algo = first(q, "X-Amz-Algorithm")
        if algo != SIGN_V4_ALGO:
            raise AuthError("SignatureDoesNotMatch", "bad algorithm")
        cred = first(q, "X-Amz-Credential").split("/")
        access_key = "/".join(cred[:-4])
        scope_date, region, service = cred[-4], cred[-3], cred[-2]
        secret = self.lookup(access_key)
        if secret is None:
            raise AuthError("InvalidAccessKeyId", "access key not found")
        timestamp = first(q, "X-Amz-Date")
        expires = int(first(q, "X-Amz-Expires") or "0")
        if not 0 < expires <= PRESIGN_EXPIRY_MAX:
            raise AuthError("AuthorizationQueryParametersError",
                            "invalid expiry")
        t = _parse_amz_date(timestamp)
        now = datetime.datetime.now(datetime.timezone.utc)
        if now > t + datetime.timedelta(seconds=expires):
            raise AuthError("AccessDenied", "request has expired")
        # A far-future X-Amz-Date would keep the URL valid for years,
        # defeating PRESIGN_EXPIRY_MAX (reference errRequestNotReadyYet).
        if t > now + datetime.timedelta(seconds=15 * 60):
            raise AuthError("AccessDenied", "request is not valid yet")
        signed_headers = first(q, "X-Amz-SignedHeaders").split(";")
        signature = first(q, "X-Amz-Signature")
        scope = f"{scope_date}/{region}/{service}/aws4_request"
        creq = canonical_request(method, path, query, headers,
                                 signed_headers, UNSIGNED_PAYLOAD,
                                 drop_query=("X-Amz-Signature",))
        sts = string_to_sign(timestamp, scope, creq)
        key = signing_key(secret, scope_date, region, service)
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, signature):
            raise AuthError("SignatureDoesNotMatch",
                            "presigned signature mismatch")
        return access_key

    @staticmethod
    def _check_skew(timestamp: str, max_skew: int = 15 * 60):
        t = _parse_amz_date(timestamp)
        now = datetime.datetime.now(datetime.timezone.utc)
        if abs((now - t).total_seconds()) > max_skew:
            raise AuthError("RequestTimeTooSkewed",
                            "request time too skewed", 403)

    # -- signing (client side, for tests and the admin CLI) ------------------

    def sign_request(self, access_key: str, secret: str, method: str,
                     path: str, query: dict[str, list[str]],
                     headers: dict[str, str],
                     payload_hash: str = UNSIGNED_PAYLOAD) -> str:
        now = datetime.datetime.now(datetime.timezone.utc)
        timestamp = now.strftime("%Y%m%dT%H%M%SZ")
        headers["x-amz-date"] = timestamp
        headers["x-amz-content-sha256"] = payload_hash
        signed = sorted(h for h in headers
                        if h == "host" or h.startswith("x-amz-"))
        scope_date = timestamp[:8]
        scope = f"{scope_date}/{self.region}/s3/aws4_request"
        creq = canonical_request(method, path, query, headers, signed,
                                 payload_hash)
        sts = string_to_sign(timestamp, scope, creq)
        key = signing_key(secret, scope_date, self.region)
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        return (f"{SIGN_V4_ALGO} Credential={access_key}/{scope}, "
                f"SignedHeaders={';'.join(signed)}, Signature={sig}")


def _parse_amz_date(timestamp: str) -> datetime.datetime:
    try:
        return datetime.datetime.strptime(
            timestamp, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc)
    except ValueError:
        try:
            return datetime.datetime.strptime(
                timestamp, "%a, %d %b %Y %H:%M:%S %Z").replace(
                tzinfo=datetime.timezone.utc)
        except ValueError as e:
            raise AuthError("AccessDenied", f"bad date: {timestamp}") from e


def dict_ci(query: dict[str, list[str]]) -> dict[str, list[str]]:
    return dict(query)


def first(q: dict[str, list[str]], key: str) -> str:
    v = q.get(key) or [""]
    return v[0]


class ChunkedSigV4Reader:
    """Reader for STREAMING-AWS4-HMAC-SHA256-PAYLOAD bodies (reference
    cmd/streaming-signature-v4.go): frames of
    ``<hex-size>;chunk-signature=<sig>\\r\\n<data>\\r\\n`` with a rolling
    per-chunk signature chain; the final 0-size chunk closes the stream."""

    def __init__(self, raw, seed_signature: str, signing_key_: bytes,
                 timestamp: str, scope: str):
        self.raw = raw
        self.prev_sig = seed_signature
        self.key = signing_key_
        self.timestamp = timestamp
        self.scope = scope
        self._buf = bytearray()
        self._eof = False

    def _read_line(self) -> bytes:
        line = bytearray()
        while True:
            c = self.raw.read(1)
            if not c:
                raise AuthError("IncompleteBody", "truncated chunk header",
                                400)
            line += c
            if line.endswith(b"\r\n"):
                return bytes(line[:-2])

    def _next_chunk(self):
        header = self._read_line()
        try:
            size_hex, _, rest = header.partition(b";")
            size = int(size_hex, 16)
            sig = rest.split(b"=", 1)[1].decode()
        except (ValueError, IndexError) as e:
            raise AuthError("SignatureDoesNotMatch",
                            f"malformed chunk header: {header!r}", 400) from e
        data = self.raw.read(size) if size else b""
        while len(data) < size:
            more = self.raw.read(size - len(data))
            if not more:
                raise AuthError("IncompleteBody", "truncated chunk", 400)
            data += more
        crlf = self.raw.read(2)
        if crlf != b"\r\n":
            raise AuthError("IncompleteBody", "missing chunk CRLF", 400)
        sts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", self.timestamp, self.scope,
            self.prev_sig, EMPTY_SHA256,
            hashlib.sha256(data).hexdigest()])
        want = hmac.new(self.key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            raise AuthError("SignatureDoesNotMatch",
                            "chunk signature mismatch", 403)
        self.prev_sig = sig
        if size == 0:
            self._eof = True
        else:
            self._buf += data

    def read(self, n: int = -1) -> bytes:
        while not self._eof and (n < 0 or len(self._buf) < n):
            self._next_chunk()
        if n < 0 or n >= len(self._buf):
            out = bytes(self._buf)
            self._buf.clear()
        else:
            out = bytes(self._buf[:n])
            del self._buf[:n]
        return out


def presign_v4(method: str, scheme: str, host: str, path: str,
               access_key: str, secret: str, region: str,
               expires_s: int) -> str:
    """Generate a presigned URL (client side of _verify_presigned —
    reference pkg/s3signer PresignV4). ``path`` is the RAW (unquoted)
    object path — parsing a joined URL string would misread keys
    containing '?' or '#'."""
    import urllib.parse
    path = path or "/"
    now = datetime.datetime.now(datetime.timezone.utc)
    timestamp = now.strftime("%Y%m%dT%H%M%SZ")
    scope = f"{timestamp[:8]}/{region}/s3/aws4_request"
    query = {
        "X-Amz-Algorithm": [SIGN_V4_ALGO],
        "X-Amz-Credential": [f"{access_key}/{scope}"],
        "X-Amz-Date": [timestamp],
        "X-Amz-Expires": [str(expires_s)],
        "X-Amz-SignedHeaders": ["host"],
    }
    headers = {"host": host}
    creq = canonical_request(method, path, query, headers, ["host"],
                             UNSIGNED_PAYLOAD)
    sts = string_to_sign(timestamp, scope, creq)
    key = signing_key(secret, timestamp[:8], region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    qs = urllib.parse.urlencode(
        [(k, v[0]) for k, v in query.items()] +
        [("X-Amz-Signature", sig)])
    return f"{scheme}://{host}{urllib.parse.quote(path)}?{qs}"
