"""CLI entry: ``python -m minio_tpu.server [--address HOST:PORT] DIR...``
— the analogue of ``minio server`` (reference cmd/server-main.go:404).
Disk args may use ellipses patterns (``/data/disk{1...8}``, expanded by
minio_tpu.dist.ellipses) and are grouped into erasure sets of 4-16
drives. ``http://host:port/path`` endpoint args select DISTRIBUTED mode:
every process gets the same full endpoint list, serves the disks whose
URL matches its --address, and reaches the rest over storage RPC
(reference dist-erasure startup; buildscripts/verify-healing.sh drives
it the same way). ``--gateway nas|s3`` serves the S3 API over a backend.
Root credentials: MINIO_TPU_ROOT_USER/_PASSWORD (MINIO_ROOT_USER/
_PASSWORD also honored; default minioadmin/minioadmin)."""
from __future__ import annotations

import argparse
import os
import sys


def _root_creds() -> tuple[str, str]:
    ak = os.environ.get("MINIO_TPU_ROOT_USER") \
        or os.environ.get("MINIO_ROOT_USER") or "minioadmin"
    sk = os.environ.get("MINIO_TPU_ROOT_PASSWORD") \
        or os.environ.get("MINIO_ROOT_PASSWORD") or "minioadmin"
    return ak, sk


def main(argv=None):
    ap = argparse.ArgumentParser(prog="minio-tpu server")
    ap.add_argument("dirs", nargs="+", help="disk directories or "
                    "ellipses patterns like /data/disk{1...8}; "
                    "http://host:port/path endpoints = distributed mode")
    ap.add_argument("--address", default="0.0.0.0:9000",
                    help="host:port to listen on; comma-separate for "
                         "additional bindings (multi-addr listener)")
    ap.add_argument("--region", default="us-east-1")
    ap.add_argument("--parity", type=int, default=None,
                    help="parity drives per set (default: drives/2)")
    ap.add_argument("--gateway",
                    choices=["nas", "s3", "hdfs", "azure", "gcs"],
                    default=None,
                    help="gateway mode: serve the S3 API over a backend "
                         "(nas: shared mount path; s3: upstream endpoint)")
    args = ap.parse_args(argv)
    ak, sk = _root_creds()
    if "," in args.address and any(
            d.startswith(("http://", "https://")) for d in args.dirs):
        ap.error("multi-addr --address is not supported in distributed "
                 "mode; pass the single URL this node serves")

    if args.gateway:
        from ..gateway import new_gateway_layer
        if len(args.dirs) != 1:
            ap.error("gateway mode takes exactly one target")
        up_ak = os.environ.get("MINIO_TPU_GATEWAY_ACCESS_KEY", ak)
        up_sk = os.environ.get("MINIO_TPU_GATEWAY_SECRET_KEY", sk)
        obj = new_gateway_layer(args.gateway, args.dirs[0], up_ak, up_sk,
                                args.region)
        banner = f"gateway {args.gateway} -> {args.dirs[0]}"
    elif any(d.startswith(("http://", "https://")) for d in args.dirs):
        return _serve_distributed(args, ak, sk)
    elif len(args.dirs) > 1 and any("{" in d for d in args.dirs) and \
            not all("{" in d for d in args.dirs):
        # the reference rejects mixed ellipses/non-ellipses endpoint args
        # (cmd/endpoint-ellipses.go): silently flattening `/p/d{1...4}
        # /extra` into one set layout would place data on a topology the
        # operator never asked for
        ap.error("invalid endpoint args: all disk args must use ellipses "
                 "patterns ({...}) or none may; mixing patterns and "
                 "plain paths is not supported")
    elif len(args.dirs) > 1 and all("{" in d for d in args.dirs):
        # multiple ellipses args = one POOL per arg (reference server
        # pool expansion: `minio server dir{1...4} dir{5...8}` is two
        # pools, cmd/endpoint-ellipses.go / erasure-server-pool.go)
        from ..dist.ellipses import expand_endpoints
        from ..dist.topology import pick_set_layout
        from ..objectlayer import ErasureSets, ServerPools
        from ..storage import XLStorage
        pools = []
        for spec in args.dirs:
            dirs = expand_endpoints([spec])
            set_count, per_set = pick_set_layout(len(dirs))
            pools.append(ErasureSets([XLStorage(d) for d in dirs],
                                     set_count, per_set,
                                     default_parity=args.parity))
        obj = ServerPools(pools)
        banner = f"erasure: {len(pools)} pools"
    else:
        from ..dist.ellipses import expand_endpoints
        dirs = expand_endpoints(args.dirs)

        from ..dist.topology import pick_set_layout
        from ..objectlayer import ErasureObjects, ErasureSets
        from ..storage import XLStorage
        disks = [XLStorage(d) for d in dirs]
        if len(disks) == 1:
            from ..fs import FSObjects
            obj = FSObjects(dirs[0])
            banner = f"FS mode on {dirs[0]}"
        else:
            set_count, per_set = pick_set_layout(len(disks))
            if set_count == 1:
                obj = ErasureObjects(disks, default_parity=args.parity)
            else:
                obj = ErasureSets(disks, set_count, per_set,
                                  default_parity=args.parity)
            banner = f"erasure: {set_count} set(s) x {per_set} drives"

    addrs = args.address.split(",")
    parsed = []
    for a in addrs:
        h, _, p = a.rpartition(":")
        try:
            parsed.append((h or "0.0.0.0", int(p)))
        except ValueError:
            ap.error(f"invalid --address entry {a!r} "
                     "(expected host:port)")
    (host, port), extra = parsed[0], parsed[1:]
    from . import S3Server
    srv = S3Server(obj, host or "0.0.0.0", int(port), args.region,
                   access_key=ak, secret_key=sk, extra_addresses=extra)
    if extra:
        banner += f"; +{len(extra)} extra listener(s)"
    if os.environ.get("MINIO_TPU_ETCD_ENDPOINTS"):
        # resolve the advertise address only when federation is actually
        # configured — gethostbyname can fail on minimal containers
        from ..dist.federation import federation_from_env
        import socket as _socket
        adv = host if host not in ("", "0.0.0.0") else \
            _socket.gethostbyname(_socket.gethostname())
        fed = federation_from_env(adv, int(port))
        if fed is not None:
            srv.enable_federation(fed)
            banner += f"; federated via etcd (domain {fed.domain})"
    _install_service_hook(srv)
    if not args.gateway:
        # background plane (scanner / MRF / auto-heal) runs on real
        # object layers; gateways proxy a backend that owns its own
        # durability (the reference skips these in gateway mode too)
        srv.start_background_services()
    print(f"{banner}; listening on {args.address}", file=sys.stderr)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass


def _install_service_hook(srv) -> None:
    """mc admin service restart/stop (reference cmd/service.go: restart
    re-execs the same argv so config/env changes load; stop exits
    cleanly). Installed for every CLI mode — single node, gateway AND
    distributed — so the admin endpoint acts instead of silently
    acking."""
    def service_signal(action: str):
        if action == "restart":
            os.execv(sys.executable, [sys.executable, "-m",
                                      "minio_tpu.server",
                                      *sys.argv[1:]])
        os._exit(0)

    srv.on_service_signal = service_signal


def _serve_distributed(args, ak: str, sk: str):
    """Distributed startup: build the Node from the full endpoint list,
    identify ourselves by --address, serve until killed."""
    import socket
    import threading

    from ..dist.node import Node
    host, _, port = args.address.rpartition(":")
    host = host or "0.0.0.0"

    def build(local_url: str) -> Node:
        return Node(args.dirs, local_url=local_url, address=host,
                    port=int(port), access_key=ak, secret_key=sk,
                    region=args.region, default_parity=args.parity)

    node = build(f"http://{host}:{port}")
    if not node.local_disks:
        node = build(f"https://{host}:{port}")
    if not node.local_disks:
        # --address 0.0.0.0 (or a host alias) matches no endpoint URL;
        # retry with any endpoint on our port whose host resolves to a
        # local interface — silently owning zero disks makes a cluster
        # that comes up dead
        local_names = {"127.0.0.1", "localhost", socket.gethostname(),
                       socket.getfqdn()}
        candidates = {e.url for e in node.endpoints
                      if e.url and e.url.rsplit(":", 1)[-1] == port
                      and e.url.split("//", 1)[-1].rsplit(":", 1)[0]
                      in local_names}
        if len(candidates) == 1:
            node = build(candidates.pop())
    if not node.local_disks:
        sys.exit(f"error: --address {args.address} matches no endpoint "
                 f"URL; pass the URL this node serves (endpoints: "
                 f"{sorted({str(e.url) for e in node.endpoints})})")
    node.start()
    if getattr(node, "server", None) is not None:
        _install_service_hook(node.server)
    print(f"distributed node listening on {args.address} "
          f"({len(node.endpoints)} endpoints)", file=sys.stderr)
    try:
        threading.Event().wait()  # serve until SIGTERM/SIGINT
    except KeyboardInterrupt:
        pass
    node.shutdown()


if __name__ == "__main__":
    main()
