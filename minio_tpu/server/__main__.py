"""CLI entry: ``python -m minio_tpu.server [--address HOST:PORT] DIR...``
— the analogue of ``minio server`` (reference cmd/server-main.go:404).
Disk args may use ellipses patterns (``/data/disk{1...8}``, expanded by
minio_tpu.dist.ellipses) and are grouped into erasure sets of 4-16 drives."""
from __future__ import annotations

import argparse
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="minio-tpu server")
    ap.add_argument("dirs", nargs="+", help="disk directories or "
                    "ellipses patterns like /data/disk{1...8}")
    ap.add_argument("--address", default="0.0.0.0:9000")
    ap.add_argument("--region", default="us-east-1")
    ap.add_argument("--parity", type=int, default=None,
                    help="parity drives per set (default: drives/2)")
    args = ap.parse_args(argv)

    from ..dist.ellipses import expand_endpoints
    dirs = expand_endpoints(args.dirs)

    from ..objectlayer import ErasureObjects, ErasureSets
    from ..storage import XLStorage
    from ..dist.topology import pick_set_layout
    disks = [XLStorage(d) for d in dirs]
    if len(disks) == 1:
        from ..fs import FSObjects
        obj = FSObjects(dirs[0])
        print(f"FS mode on {dirs[0]}", file=sys.stderr)
    else:
        set_count, per_set = pick_set_layout(len(disks))
        if set_count == 1:
            obj = ErasureObjects(disks, default_parity=args.parity)
        else:
            obj = ErasureSets(disks, set_count, per_set,
                              default_parity=args.parity)
        print(f"erasure: {set_count} set(s) x {per_set} drives",
              file=sys.stderr)

    host, _, port = args.address.rpartition(":")
    from . import S3Server
    srv = S3Server(obj, host or "0.0.0.0", int(port), args.region)
    print(f"listening on {args.address}", file=sys.stderr)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
