#!/usr/bin/env python
"""North-star benchmark: erasure encode/reconstruct GiB/s at 16+4, 1 MiB block.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N, "extra": {...}}

The headline metric is BASELINE config 1/2's shape (16+4 encode at 1 MiB
blocks, batch 128); "extra" carries the other BASELINE configs measured the
same way: 2-shard reconstruct (config 3) and the batched heal rebuild
(config 5's device kernel). vs_baseline divides TPU device throughput by a
locally measured CPU AVX2 single-core encode (the same nibble-shuffle galois
kernel the reference uses via klauspost/reedsolomon; see
minio_tpu/native/gf256_simd.cpp).

Timing note (recorded in .claude/skills/verify/SKILL.md): on the axon TPU
platform block_until_ready() returns immediately and any device_get costs a
~30-70 ms tunnel round-trip, so device time is measured as the slope of
N-dispatch chains with a single final sync.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def measure_slope(fn, n_hi: int = 101, reps: int = 3) -> float:
    """Per-call device seconds: slope between 1-call and n_hi-call chains.

    fn(n) must dispatch n times and hard-sync once at the end.
    """
    t1 = min(fn(1) for _ in range(reps))
    tn = min(fn(n_hi) for _ in range(max(1, reps - 1)))
    return max((tn - t1) / (n_hi - 1), 1e-9)


def main() -> None:
    K, M, BLOCK, B = 16, 4, 1 << 20, 128
    shard = BLOCK // K  # 64 KiB
    rng = np.random.default_rng(0)

    # --- CPU baseline (AVX2 single core, like the reference's per-core SIMD)
    from minio_tpu import native
    from minio_tpu.ops import gf256
    pmat = gf256.build_matrix(K, M)[K:]
    data1 = rng.integers(0, 256, (K, shard), dtype=np.uint8)
    native.cpu_encode(pmat, data1, M)  # warm
    n = 100
    t0 = time.perf_counter()
    for _ in range(n):
        native.cpu_encode(pmat, data1, M)
    cpu_gibs = BLOCK * n / (time.perf_counter() - t0) / (1 << 30)
    log(f"cpu avx2 encode 16+4 @1MiB: {cpu_gibs:.2f} GiB/s "
        f"(avx2={native.load_gf256().gf256_has_avx2()})")

    # --- TPU path (batched kernels, device-resident)
    import jax
    import jax.numpy as jnp
    from minio_tpu.ops import rs_jax
    log(f"jax backend: {jax.default_backend()} devices: {jax.devices()}")
    _, mm_batch, mm_batch_per = rs_jax._resolve_backend("auto")

    def bench_op(label, masks_np, w, batched_per=False):
        masks = jnp.asarray(masks_np)
        op = mm_batch_per if batched_per else mm_batch
        timed = jax.jit(lambda ms, xs: jnp.sum(op(ms, xs)[..., :2]))
        _ = jax.device_get(timed(masks, w))  # compile + warm

        def chain(n):
            t0 = time.perf_counter()
            s = None
            for _ in range(n):
                s = timed(masks, w)
            _ = jax.device_get(s)
            return time.perf_counter() - t0

        per = measure_slope(chain)
        gibs = B * BLOCK / per / (1 << 30)
        log(f"{label}: {per*1e6:.0f} us/batch -> {gibs:.1f} GiB/s")
        return gibs

    data = rng.integers(0, 256, (B, K, shard), dtype=np.uint8)
    w = jnp.asarray(rs_jax.pack_shards(data))

    # config 1/2: encode 16+4 @ 1 MiB, batch 128
    enc_gibs = bench_op(f"tpu encode 16+4 @1MiB x{B}",
                        gf256.coeff_masks(pmat), w)

    # config 3: 2-shard reconstruct (shared loss pattern across the batch)
    codec = rs_jax.get_codec(K, M)
    present = tuple(i for i in range(K + M) if i not in (2, 9))[:K]
    rec_masks = codec.target_masks_np(present, (2, 9))
    rec_gibs = bench_op(f"tpu reconstruct 16+4 2-loss @1MiB x{B}",
                        rec_masks, w)

    # config 5: batched heal rebuild — per-element masks, mixed loss patterns
    heal_masks = np.stack([
        codec.target_masks_np(
            tuple(j for j in range(K + M) if j not in (i % K, K + i % M))[:K],
            (i % K, K + i % M))
        for i in range(B)])
    heal_gibs = bench_op(f"tpu batched heal rebuild 16+4 x{B} mixed-loss",
                         jnp.asarray(heal_masks), w, batched_per=True)

    print(json.dumps({
        "metric": f"erasure_encode_gibs_16+4_1MiB_batch{B}",
        "value": round(enc_gibs, 2),
        "unit": "GiB/s",
        "vs_baseline": round(enc_gibs / cpu_gibs, 2),
        "extra": {
            "cpu_avx2_encode_gibs": round(cpu_gibs, 2),
            "reconstruct_2loss_gibs": round(rec_gibs, 2),
            "reconstruct_vs_cpu": round(rec_gibs / cpu_gibs, 2),
            "batched_heal_rebuild_gibs": round(heal_gibs, 2),
        },
    }))


if __name__ == "__main__":
    main()
