#!/usr/bin/env python
"""North-star benchmark: erasure encode/reconstruct GiB/s at 16+4, 1 MiB
block, plus p99 heal-shard latency — ALL FIVE configs of BASELINE.md:

  1. 4+2, 1 MiB block, single PutObject end-to-end (object layer -> bitrot
     -> disk), plus the same for 16+4.
  2. 8+4 encode-only block-size sweep, 64 KiB - 4 MiB.
  3. 16+4 two-shard-loss reconstruct, batch 128.
  4. 16+4 FUSED HighwayHash verify + reconstruct (per-chunk digests checked
     on device in the same launch as the rebuild).
  5. 32-drive-style batched heal: 128 concurrent objects, mixed loss
     patterns, per-element rebuild matrices.
  plus: p50/p99 latency of a single 16+4 heal-shard rebuild THROUGH the
     dispatch queue at 1/8/128 concurrent requesters.

`--chaos` additionally arms a 1-slow-disk + 1-dead-disk fault profile at
16+4 (docs/fault.md) and reports GET / heal-shard p50/p99 for the clean
and degraded runs side by side under `extra.chaos`.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N, "extra": {...}}

vs_baseline divides TPU device throughput by a locally measured CPU AVX2
single-core encode (the same nibble-shuffle galois kernel the reference
uses via klauspost/reedsolomon; see minio_tpu/native/gf256_simd.cpp).

Timing note (recorded in .claude/skills/verify/SKILL.md): on the axon TPU
platform block_until_ready() returns immediately and any device_get costs a
~60-120 ms tunnel round-trip whose run-to-run variance swamps short
dispatch chains (the r03->r04 "24% encode regression" and the wandering
sweep dip were exactly this noise). Device kernel time is therefore
measured DEVICE-RESIDENT: one jitted lax.fori_loop dispatch runs the kernel
N times with a carried scalar dependency (so XLA can't hoist the
loop-invariant call), and the per-iteration time is the slope between N=1
and N=1025 — tunnel round-trip noise divides by 1024. Latency percentiles
are wall-clock through the dispatch queue and therefore INCLUDE the tunnel
round-trip — they are what a caller of this deployment actually observes.
"""
from __future__ import annotations

import io
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def measure_slope(fn, n_hi: int = 1025, reps: int = 3) -> float:
    """Per-iteration device seconds: slope between a 1-iteration and an
    n_hi-iteration run. fn(n) runs the kernel n times (device-resident
    loop) and hard-syncs; the slope cancels dispatch + tunnel round-trip.
    """
    t1 = min(fn(1) for _ in range(reps))
    tn = min(fn(n_hi) for _ in range(max(1, reps - 1)))
    return max((tn - t1) / (n_hi - 1), 1e-9)


def cpu_baseline(rng) -> float:
    """Single-core AVX2 GF(256) encode at 16+4 / 1 MiB (the reference's
    klauspost/reedsolomon per-core shape)."""
    from minio_tpu import native
    from minio_tpu.ops import gf256
    K, M, BLOCK = 16, 4, 1 << 20
    pmat = gf256.build_matrix(K, M)[K:]
    data1 = rng.integers(0, 256, (K, BLOCK // K), dtype=np.uint8)
    native.cpu_encode(pmat, data1, M)  # warm
    n = 100
    t0 = time.perf_counter()
    for _ in range(n):
        native.cpu_encode(pmat, data1, M)
    gibs = BLOCK * n / (time.perf_counter() - t0) / (1 << 30)
    log(f"cpu avx2 encode 16+4 @1MiB: {gibs:.2f} GiB/s "
        f"(avx2={native.load_gf256().gf256_has_avx2()})")
    return gibs


def device_configs(rng) -> dict:
    """Device-kernel configs 2/3/4/5 via the production kernels: encode
    rides the static-specialized pallas kernel (what encode_words_batch /
    the dispatch queue run), reconstruct/heal/fused the dynamic-mask one.

    Each config is timed as ONE jitted lax.fori_loop whose body re-runs the
    kernel with a carried scalar folded into its inputs (masks ^ c, or the
    static kernel's c hook) — a data dependency XLA cannot hoist, so N
    iterations really execute on device and the tunnel round-trip appears
    once, not N times.
    """
    import jax
    import jax.numpy as jnp
    from minio_tpu.native import highwayhash as hhn
    from minio_tpu.ops import fused as fused_mod
    from minio_tpu.ops import gf256, rs_jax
    log(f"jax backend: {jax.default_backend()} devices: {jax.devices()}")
    _, mm_batch, mm_batch_per = rs_jax._resolve_backend("auto")
    out: dict = {}

    def bench_loop(label, nbytes_per_elem, body, *args):
        """body(c, *args) -> output array; carried scalar c = out[...0]."""
        @jax.jit
        def loop(n, *a):
            def it(_, c):
                return body(c, *a).reshape(-1)[0]
            return jax.lax.fori_loop(0, n, it, jnp.uint32(0))

        # sync via device_get ONLY: on axon block_until_ready can return
        # before execution (enqueue-only), which times the dispatch, not
        # the kernel; the fetch round-trip cancels in the N=1 vs N=1025
        # slope
        _ = jax.device_get(loop(1, *args))  # compile + warm

        def run(n):
            t0 = time.perf_counter()
            _ = jax.device_get(loop(n, *args))
            return time.perf_counter() - t0

        per = measure_slope(run)
        gibs = nbytes_per_elem / per / (1 << 30)
        log(f"{label}: {per*1e6:.0f} us/batch -> {gibs:.1f} GiB/s")
        return gibs

    K, M, BLOCK, B = 16, 4, 1 << 20, 128
    shard = BLOCK // K
    data = rng.integers(0, 256, (B, K, shard), dtype=np.uint8)
    w = jnp.asarray(rs_jax.pack_shards(data))
    codec = rs_jax.get_codec(K, M)

    def enc_body(codec):
        if codec._static_encode:
            from minio_tpu.ops import rs_pallas
            return lambda c, xs: rs_pallas.gf_matmul_static_batch(
                codec.parity_rows, xs, c)
        masks = jnp.asarray(gf256.coeff_masks(codec.parity_rows))
        return lambda c, xs: mm_batch(masks ^ c, xs)

    out["encode_16p4_1MiB_b128"] = bench_loop(
        f"tpu encode 16+4 @1MiB x{B}", B * BLOCK, enc_body(codec), w)

    present = tuple(i for i in range(K + M) if i not in (2, 9))[:K]
    rec_masks = jnp.asarray(codec.target_masks_np(present, (2, 9)))
    out["reconstruct_2loss_16p4_b128"] = bench_loop(
        f"tpu reconstruct 16+4 2-loss @1MiB x{B}", B * BLOCK,
        lambda c, ms, xs: mm_batch(ms ^ c, xs), rec_masks, w)

    # config 2: 8+4 encode sweep 64 KiB - 4 MiB (batch sized to keep ~128
    # MiB of source data per launch), through the production encode kernel
    sweep = {}
    codec84 = rs_jax.get_codec(8, 4)
    for bs in (1 << 16, 1 << 18, 1 << 20, 1 << 22):
        bsz = max(1, (128 << 20) // bs)
        d = rng.integers(0, 256, (bsz, 8, bs // 8), dtype=np.uint8)
        ws = jnp.asarray(rs_jax.pack_shards(d))
        sweep[f"{bs >> 10}KiB"] = round(bench_loop(
            f"tpu encode 8+4 @{bs >> 10}KiB x{bsz}", bsz * bs,
            enc_body(codec84), ws), 2)
    out["encode_sweep_8p4"] = sweep

    # config 4: fused bitrot verify + 2-loss reconstruct, 16 KiB chunks —
    # measured with BOTH device hashes: MUR3X256 (u32-native, the
    # framework default) and HighwayHash (u64-emulated, reference-parity)
    from minio_tpu.erasure.bitrot import HIGHWAY_KEY
    from minio_tpu.native import mur3py
    from minio_tpu.ops import hh_jax, mur3_jax
    C = 16384
    nc = shard // C
    rec_masks_np = codec.target_masks_np(present, (2, 9))  # [8, o=2, K]
    rec_masks_b = jnp.asarray(np.broadcast_to(
        rec_masks_np, (B,) + rec_masks_np.shape))
    for algo_name, algo_id, batch_hash, key_fn in (
            ("mur3", 1, mur3py.hash256_batch, mur3_jax._key_words),
            ("hh", 0, hhn.hash256_batch, hh_jax._key_words)):
        digs_np = np.stack([
            batch_hash(HIGHWAY_KEY,
                       data[b].reshape(K * nc, C)).reshape(K, nc * 32)
            for b in range(B)])
        digs = jnp.asarray(digs_np.view(np.uint32).reshape(B, K, nc * 8))
        # the PRODUCTION kernel resolution (fused_fn_for): mur3 rides the
        # Pallas hash kernel unless pipeline.device_hash=jnp routes back
        fused_fn = fused_mod.fused_fn_for(HIGHWAY_KEY, shard,
                                          mm_batch_per, C, algo_id)

        def body_fused(c, ms, xs, dg, fused_fn=fused_fn):
            # the hash verify is jnp (not pallas), and xs/dg are loop
            # constants: unless the DATA depends on the carry, XLA hoists
            # the whole verify subgraph out of the loop and times only the
            # rebuild (this made HH read 174 GiB/s, 17x its real rate).
            # xs ^ c forces a re-hash per iteration (~0.3 ms of extra
            # elementwise traffic, <10% of the fused time); summing v
            # keeps every verdict lane live
            o, v = fused_fn(ms, xs ^ c, dg)
            return o.reshape(-1)[0] + jnp.sum(v.astype(jnp.uint32))

        out[f"fused_verify_reconstruct_16p4_b128_{algo_name}"] = bench_loop(
            f"tpu FUSED {algo_name}-verify+reconstruct 16+4 x{B}",
            B * BLOCK, body_fused, rec_masks_b, w, digs)
    out["fused_verify_reconstruct_16p4_b128"] = \
        out["fused_verify_reconstruct_16p4_b128_mur3"]

    # PUT-side device hash lane: fused encode+hash (parity + per-chunk
    # digests of all k+m shards in one launch — what the dispatch queue's
    # encode_hashed flush runs)
    enc_hash_fn = fused_mod.encode_hashed_fn_for(
        HIGHWAY_KEY, shard, codec.encode_words_batch, C, 1)

    def body_enc_hash(c, xs):
        par, dg = enc_hash_fn(xs ^ c)
        return par.reshape(-1)[0] + jnp.sum(dg.astype(jnp.uint32))

    out["fused_encode_hash_16p4_b128"] = bench_loop(
        f"tpu FUSED encode+hash 16+4 x{B}", B * BLOCK, body_enc_hash, w)

    # config 5: batched heal rebuild — per-element masks, mixed loss
    heal_masks = np.stack([
        codec.target_masks_np(
            tuple(j for j in range(K + M) if j not in (i % K, K + i % M))[:K],
            (i % K, K + i % M))
        for i in range(B)])
    out["batched_heal_rebuild_b128"] = bench_loop(
        f"tpu batched heal rebuild 16+4 x{B} mixed-loss", B * BLOCK,
        lambda c, ms, xs: mm_batch_per(ms ^ c, xs),
        jnp.asarray(heal_masks), w)
    return out


def bench_dir() -> str | None:
    """Backing dir for the e2e disks: MINIO_TPU_BENCH_DIR, else /dev/shm
    when it has headroom (the e2e configs measure the framework data plane,
    not the speed of whatever disk backs /tmp), else the default tmp."""
    env = os.environ.get("MINIO_TPU_BENCH_DIR")
    if env:
        return env
    try:
        st = os.statvfs("/dev/shm")
        if st.f_bavail * st.f_frsize > (4 << 30):
            return "/dev/shm"
    except OSError:
        pass
    return None


def host_profile(rng) -> dict:
    """Primitive single-thread rates that bound the e2e configs on this
    host: the serial PUT chain is read + MD5(ETag) + fused encode+hash +
    framed file write, so on an N-core host the achievable ceiling is
    roughly min(stage rates) (pipelined) or 1/sum(1/rates) on one core.
    Recorded so the e2e numbers are interpretable against the hardware."""
    import tempfile as tf
    import time as tm
    out = {"cpus": os.cpu_count()}
    buf = rng.integers(0, 256, 32 << 20, dtype=np.uint8).tobytes()
    import hashlib
    h = hashlib.md5()
    t0 = tm.perf_counter()
    h.update(buf)
    out["md5_gibs"] = round(len(buf) / (tm.perf_counter() - t0) / (1 << 30), 2)
    d = tf.mkdtemp(dir=bench_dir())
    try:
        t0 = tm.perf_counter()
        with open(os.path.join(d, "f"), "wb") as f:
            f.write(buf)
        out["file_write_gibs"] = round(
            len(buf) / (tm.perf_counter() - t0) / (1 << 30), 2)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    try:
        from minio_tpu import native
        from minio_tpu.ops import gf256
        pmat = gf256.build_matrix(4, 2)[4:]
        native.put_block(buf[:1 << 20], 1 << 20, pmat, 4, 2, 1 << 18,
                         16384, b"\x00" * 32)
        t0 = tm.perf_counter()
        for i in range(16):
            native.put_block(buf[i << 20:(i + 1) << 20], 1 << 20, pmat,
                             4, 2, 1 << 18, 16384, b"\x00" * 32)
        out["native_put_block_gibs"] = round(
            16 * (1 << 20) / (tm.perf_counter() - t0) / (1 << 30), 2)
    except Exception:  # noqa: BLE001 — no native build
        pass
    log(f"host: {out}")
    return out


def _host_profile_summary(snap) -> dict:
    """Continuous-profiler window -> the compact ``host_profile``
    bench leaf (ISSUE 14): top-10 folded host frames + subsystem
    shares — the evidence channel for where host CPU goes during the
    measured window (docs/observability.md "Continuous profiling").
    A DELTA over the always-on base sampler: the measured section pays
    nothing beyond the standing base rate, so the headline numbers it
    rides beside stay untaxed. Leaves here are registered NON_HEADLINE
    in tools/bench_compare.py: shares shift with host load and must
    inform, not gate."""
    from minio_tpu.obs import profiler as prof
    rep = prof.delta_report(snap, n=10)
    return {"samples": rep["samples"],
            "sample_hz": rep["sample_hz"],
            "top_frames": rep.get("top_frames", []),
            "subsystems": rep["subsystems"],
            "roles": rep["roles"],
            "lockwait_share": rep["lockwait_share"]}


def e2e_put(rng) -> dict:
    """Config 1: end-to-end PutObject through object layer -> erasure ->
    bitrot writers -> local disks, 4+2 and 16+4, serial and 8-way
    parallel. Each block reads into a pooled buffer (zero-copy ingest)
    and runs the fused native pipeline (split+encode+hash+frame+pwrite in
    one GIL-releasing mt_put_block_fds call); the ETag is the fused
    pipeline hash (md5 over the bitrot digest stream, ~0.2% of payload),
    so no host stage hashes payload bytes — the ceiling is the native
    block rate and the file-write bound, not the old single-CPU MD5.
    ``put_stage_breakdown`` attributes one serial PUT's seconds per
    stage."""
    import threading
    from minio_tpu.obs import stages as obstages
    from minio_tpu.objectlayer import ErasureObjects
    from minio_tpu.storage import XLStorage
    out = {}
    obj_size = 64 << 20
    body = rng.integers(0, 256, obj_size, dtype=np.uint8).tobytes()
    for k, m in ((4, 2), (16, 4)):
        root = tempfile.mkdtemp(prefix=f"bench{k}p{m}-", dir=bench_dir())
        try:
            disks = [XLStorage(os.path.join(root, f"d{i}"))
                     for i in range(k + m)]
            ol = ErasureObjects(disks, default_parity=m)
            ol.make_bucket("b")
            ol.put_object("b", "warm", io.BytesIO(body[:1 << 20]), 1 << 20)
            reps = 3
            t0 = time.perf_counter()
            for r in range(reps):
                ol.put_object("b", f"o{r}", io.BytesIO(body), obj_size)
            dt = time.perf_counter() - t0
            gibs = obj_size * reps / dt / (1 << 30)
            # stage attribution for ONE serial PUT (satellite of ROADMAP
            # item 1): seconds spent in body-read / ETag / encode+hash /
            # shard-write, so pipeline wins are explainable stage by
            # stage across BENCH rounds (overlapped stages each charge
            # their own wall, so the sum may exceed the PUT wall)
            with obstages.collect() as stc:
                t0 = time.perf_counter()
                ol.put_object("b", "staged", io.BytesIO(body), obj_size)
                put_wall = time.perf_counter() - t0
            stage_brk = {"wall_s": round(put_wall, 4), **stc.snapshot()}
            log(f"e2e {k}+{m} put stages: {stage_brk}")
            t0 = time.perf_counter()
            assert ol.get_object_buffer("b", "o0") == body
            get_gibs = obj_size / (time.perf_counter() - t0) / (1 << 30)

            def worker(j):
                ol.put_object("b", f"p{j}", io.BytesIO(body), obj_size)

            threads = [threading.Thread(target=worker, args=(j,))
                       for j in range(8)]
            # host-CPU attribution of the 16+4 par8 PUT (ISSUE 14): a
            # base-aggregate delta over exactly the measured section —
            # the BENCH_r07 evidence for what bounds e2e PUT, at zero
            # added cost to the gating headline it rides beside
            prof_snap = None
            if (k, m) == (16, 4):
                from minio_tpu.obs import profiler as prof
                prof_snap = prof.agg_snapshot(full=True)
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            par = 8 * obj_size / (time.perf_counter() - t0) / (1 << 30)
            if prof_snap is not None:
                out["host_profile"] = _host_profile_summary(prof_snap)
                log(f"e2e 16+4 par8 host profile: "
                    f"{out['host_profile']['subsystems']}")

            read_errs: list = []

            def reader(j):
                try:
                    # zero-copy accessor: compares equal without the
                    # final full-object tobytes pass (get_object_bytes'
                    # GIL-held copy was a residual par8 serializer)
                    if ol.get_object_buffer("b", f"p{j}") != body:
                        raise AssertionError(f"p{j} bytes mismatch")
                except BaseException as e:  # noqa: BLE001
                    read_errs.append(e)

            threads = [threading.Thread(target=reader, args=(j,))
                       for j in range(8)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if read_errs:  # a thread failure must not inflate the number
                raise read_errs[0]
            gpar = 8 * obj_size / (time.perf_counter() - t0) / (1 << 30)
            log(f"e2e {k}+{m} 64MiB: put {gibs:.2f} get {get_gibs:.2f} "
                f"par8 {par:.2f} get_par8 {gpar:.2f} GiB/s")
            out[f"{k}p{m}"] = {"put": round(gibs, 2),
                               "get": round(get_gibs, 2),
                               "put_par8": round(par, 2),
                               "get_par8": round(gpar, 2),
                               "put_stage_breakdown": stage_brk}
        finally:
            shutil.rmtree(root, ignore_errors=True)
    return out


def fsync_put(rng) -> dict:
    """Durability tax (docs/durability.md): 8-way-parallel PUT GiB/s at
    16+4 / 1 MiB objects under fsync=off|batched|always. batched's wall
    time includes the flusher barrier so the number is the cost of
    durability actually achieved, not of deferring it past the
    measurement. Best-of-2 reps per mode after a discarded warmup pass:
    small-object par8 runs swing 2x run-to-run on this 1-core host, and
    a single sample can report a phantom 50% overhead (or a phantom
    speedup) that is pure scheduler noise."""
    import threading

    from minio_tpu.objectlayer import ErasureObjects
    from minio_tpu.storage import XLStorage
    from minio_tpu.storage.durability import flusher
    K, M, OBJ, N_PER, REPS = 16, 4, 1 << 20, 16, 2
    body = rng.integers(0, 256, OBJ, dtype=np.uint8).tobytes()
    out: dict = {}
    prev = os.environ.get("MINIO_TPU_FSYNC")

    def one_rep(mode) -> float:
        root = tempfile.mkdtemp(prefix=f"benchfsync-{mode}-",
                                dir=bench_dir())
        try:
            disks = [XLStorage(os.path.join(root, f"d{i}"))
                     for i in range(K + M)]
            ol = ErasureObjects(disks, default_parity=M)
            ol.make_bucket("b")
            ol.put_object("b", "warm", io.BytesIO(body), OBJ)

            def worker(j):
                for i in range(N_PER):
                    ol.put_object("b", f"o{j}-{i}",
                                  io.BytesIO(body), OBJ)

            threads = [threading.Thread(target=worker, args=(j,))
                       for j in range(8)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if mode == "batched":
                flusher().flush(timeout=30.0)
            dt = time.perf_counter() - t0
            return 8 * N_PER * OBJ / dt / (1 << 30)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    try:
        os.environ["MINIO_TPU_FSYNC"] = "off"
        one_rep("off")  # warmup: first par8 run pays one-time init
        for mode in ("off", "batched", "always"):
            os.environ["MINIO_TPU_FSYNC"] = mode
            out[mode] = round(max(one_rep(mode) for _ in range(REPS)), 3)
        if out.get("off"):
            out["batched_overhead_pct"] = round(
                100.0 * (1.0 - out["batched"] / out["off"]), 1)
            out["always_overhead_pct"] = round(
                100.0 * (1.0 - out["always"] / out["off"]), 1)
        log(f"fsync par8 16+4 1MiB PUT GiB/s: {out}")
    finally:
        if prev is None:
            os.environ.pop("MINIO_TPU_FSYNC", None)
        else:
            os.environ["MINIO_TPU_FSYNC"] = prev
    return out


def heal_latency(rng) -> dict:
    """p50/p99 wall-clock latency of ONE 16+4 heal-shard rebuild (1 MiB
    block, 2 lost shards) through the dispatch queue, at 1/8/128 concurrent
    requesters — the north-star's latency half. Measured on BOTH routes
    (MINIO_TPU_DISPATCH_MODE=cpu and =device) so the deployment's actual
    choice is informed: through the axon tunnel the device route pays the
    full round-trip per flush; on a PCIe-attached chip it wins."""
    import threading

    import jax
    from minio_tpu.ops import rs_jax
    from minio_tpu.runtime.dispatch import global_queue
    K, M, BLOCK = 16, 4, 1 << 20
    shard = BLOCK // K
    codec = rs_jax.get_codec(K, M)
    q = global_queue()
    present = tuple(i for i in range(K + M) if i not in (3, 17))[:K]
    masks = codec.target_masks_np(present, (3, 17))
    words = rs_jax.pack_shards(
        rng.integers(0, 256, (K, shard), dtype=np.uint8))

    def run_mode(mode: str) -> dict:
        # percentiles come from the SAME last-minute sliding-window class
        # the server exports as minio_tpu_heal_shard_latency_p99_seconds
        # (minio_tpu/obs/latency.py) — bench numbers and production
        # metrics cannot diverge in method. Runs longer than the window
        # therefore report steady-state (last-minute) percentiles.
        from minio_tpu.obs import latency as obslat

        # warm every pow2 batch shape the timed runs can hit (a first-time
        # jit compile inside the timed region would own the p99)
        for warm_burst in (1, 2, 8, 16, 64, 128, 128):
            futs = [q.masked(codec, words, masks) for _ in range(warm_burst)]
            for f in futs:
                f.result()
        res = {}
        for conc in (1, 8, 128):
            n_ops = 40 if conc == 1 else max(conc * 3, 120)
            win = obslat.reset_window("kernel", op="heal_shard")

            def worker(count):
                for _ in range(count):
                    t0 = time.perf_counter()
                    q.masked(codec, words, masks).result()
                    obslat.observe("kernel", time.perf_counter() - t0,
                                   BLOCK, op="heal_shard")

            per_worker = max(1, n_ops // conc)
            threads = [threading.Thread(target=worker, args=(per_worker,))
                       for _ in range(conc)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            n_done = per_worker * conc
            ps = win.percentiles((0.5, 0.99))
            p50 = ps[0.5] * 1e3
            p99 = ps[0.99] * 1e3
            thr = n_done * BLOCK / wall / (1 << 30)
            log(f"heal-shard latency [{mode}] conc={conc}: p50={p50:.1f}ms "
                f"p99={p99:.1f}ms agg={thr:.2f} GiB/s ({n_done} ops, "
                f"{win.count()} in window)")
            res[f"conc{conc}"] = {"p50_ms": round(p50, 1),
                                  "p99_ms": round(p99, 1),
                                  "agg_gibs": round(thr, 2)}
        return res

    out = {}
    prev = os.environ.get("MINIO_TPU_DISPATCH_MODE")
    modes = ["cpu"] + (["device"]
                       if jax.default_backend() != "cpu" else [])
    # host-CPU attribution across the heal configs (ISSUE 14): where
    # the dispatcher/completer threads spend the heal-shard walls — a
    # base-aggregate delta, so the gating heal percentiles pay nothing
    from minio_tpu.obs import profiler as prof
    prof_snap = prof.agg_snapshot(full=True)
    try:
        for mode in modes:
            os.environ["MINIO_TPU_DISPATCH_MODE"] = mode
            out[mode] = run_mode(mode)
    finally:
        if prev is None:
            os.environ.pop("MINIO_TPU_DISPATCH_MODE", None)
        else:
            os.environ["MINIO_TPU_DISPATCH_MODE"] = prev
    out["host_profile"] = _host_profile_summary(prof_snap)
    log(f"heal host profile: {out['host_profile']['subsystems']}")
    st = q.stats()
    prof = q._get_profile()
    out["dispatch"] = {
        "batches": st["batches"], "cpu_batches": st["cpu_batches"],
        "device_batches": st["device_batches"],
        "cpu_items": st["cpu_items"], "device_items": st["device_items"],
        "hold_events": st["hold_events"],
        "hold_seconds": st["hold_seconds"],
        # QoS scheduler telemetry: forced-device runs through a slow
        # link are expected to SPILL most items back to the CPU
        # executor (bounded p99 instead of a multi-second backlog)
        "spilled_items": st["spilled_items"],
        "spilled_batches": st["spilled_batches"],
        "spill_reasons": st["spill_reasons"],
        "deadline_misses": st["deadline_misses"],
        # per-device flush lanes (ISSUE 11): diverts + residual queued
        # bytes per lane; the full mesh scaling story is MULTICHIP's
        # (__graft_entry__.multichip_bench), single-chip hosts report
        # an empty lane map here
        "lane_diverts": st["lane_diverts"],
        "lane_queued_bytes": st["lane_queued_bytes"],
        "avg_batch": round(st["avg_batch"], 2),
        "device_pipeline": __import__(
            "minio_tpu.runtime.dispatch",
            fromlist=["DEVICE_PIPELINE"]).DEVICE_PIPELINE,
        "completers": q.completer_count,
        "link_rt_ms": round(prof.rt_s * 1e3, 1) if prof else None,
        "link_up_gibs": round(prof.up_gibs, 3) if prof else None,
        "link_down_gibs": round(prof.down_gibs, 3) if prof else None,
        "link_cpu_gibs": round(prof.cpu_gibs, 2) if prof else None,
    }
    return out


def interactive_lane_extra(rng) -> dict:
    """ISSUE 13: heal-shard wall p50/p99 at conc=8 and conc=128 through
    BOTH device-lane disciplines — the bulk coalescing lane
    (``qos.device_stream(STREAM_BULK)``) vs the interactive lane
    (bounded <=8 batches on a dedicated dispatcher, deadline-aware
    sizing, async on_ready completion, donated inputs on TPU). Leaves
    are ``heal_p50_s``/``heal_p99_s`` (down-better headline metrics for
    tools/bench_compare). On a TPU host the acceptance target is device
    heal-shard p99 within 5x of CPU at conc=8 while bulk encode stays
    >=100 GiB/s (ROADMAP item 2); on a CPU-only host both lanes run the
    CPU route and the number documents the lane overheads instead."""
    import threading

    from minio_tpu import qos
    from minio_tpu.ops import rs_jax
    from minio_tpu.runtime.dispatch import global_queue
    K, M, BLOCK = 16, 4, 1 << 20
    shard = BLOCK // K
    codec = rs_jax.get_codec(K, M)
    q = global_queue()
    present = tuple(i for i in range(K + M) if i not in (3, 17))[:K]
    masks = codec.target_masks_np(present, (3, 17))
    words = rs_jax.pack_shards(
        rng.integers(0, 256, (K, shard), dtype=np.uint8))

    def pcts(vals: list[float]) -> dict:
        vs = sorted(vals)
        return {"heal_p50_s": round(vs[len(vs) // 2], 6),
                "heal_p99_s": round(
                    vs[min(len(vs) - 1, int(0.99 * len(vs)))], 6)}

    def run_leg(stream: str, conc: int) -> dict:
        # warm the pow2 batch shapes this leg can hit
        with qos.device_stream(stream):
            futs = [q.masked(codec, words, masks)
                    for _ in range(min(conc, 8))]
            for f in futs:
                f.result()
        n_ops = 64 if conc == 8 else 256
        per_worker = max(1, n_ops // conc)
        walls: list[float] = []
        wlock = threading.Lock()

        def worker():
            with qos.device_stream(stream):
                for _ in range(per_worker):
                    t0 = time.perf_counter()
                    q.masked(codec, words, masks).result()
                    dt = time.perf_counter() - t0
                    with wlock:
                        walls.append(dt)

        threads = [threading.Thread(target=worker)
                   for _ in range(conc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return pcts(walls)

    out: dict = {}
    for stream in (qos.STREAM_BULK, qos.STREAM_INTERACTIVE):
        leg: dict = {}
        for conc in (8, 128):
            leg[f"conc{conc}"] = run_leg(stream, conc)
            log(f"interactive_lane [{stream}] conc={conc}: "
                f"p50={leg[f'conc{conc}']['heal_p50_s'] * 1e3:.1f}ms "
                f"p99={leg[f'conc{conc}']['heal_p99_s'] * 1e3:.1f}ms")
        out[stream] = leg
    out["lane"] = q.stats()["interactive_lane"]
    return {"interactive_lane": out}


def chaos_profile(rng) -> dict:
    """--chaos: the degraded-operation half of the north-star. A 16+4
    set of 1 MiB objects is measured clean, then with a 1-slow-disk
    (delay(200) on every shard read) + 1-dead-disk (typed DiskNotFound
    on every op) profile armed through the production fault registry
    (docs/fault.md) — the same rules an operator would arm via
    `mc admin`-style POST /minio/admin/v3/fault. Reported side by side:
    GET p50/p99 (hedged reads route around the straggler; the health
    tracker trips the dead disk to fast-fail), heal-shard p50/p99 wall
    time (each heal rebuilds toward the dead disk under a slow source),
    plus the fired/won hedge counters and final disk health states.
    Both passes pin MINIO_TPU_GET_PATH=dispatch so they measure the
    same (Python shard-read) code path — chaos runs always take it, and
    its shard reads feed the adaptive hedge threshold's p95 window."""
    import threading

    from minio_tpu import fault
    from minio_tpu.objectlayer import ErasureObjects
    from minio_tpu.obs.metrics import counters_snapshot
    from minio_tpu.storage import XLStorage
    K, M, OBJ = 16, 4, 1 << 20
    N_OBJ, GET_REPS, DELAY_MS = 8, 4, 200.0
    body = rng.integers(0, 256, OBJ, dtype=np.uint8).tobytes()
    root = tempfile.mkdtemp(prefix="benchchaos-", dir=bench_dir())
    ol = None  # the finally below must not NameError if setup raises
    prev_path = os.environ.get("MINIO_TPU_GET_PATH")
    os.environ["MINIO_TPU_GET_PATH"] = "dispatch"
    # probe cadence must undercut the cleanup join(timeout=2) below, or
    # a tripped disk's probe thread outlives the rmtree'd backing dir
    prev_cool = os.environ.get("MINIO_TPU_HEALTH_COOLDOWN_S")
    os.environ["MINIO_TPU_HEALTH_COOLDOWN_S"] = "0.5"
    out: dict = {"profile": f"slow=delay({DELAY_MS:.0f}ms) dead=DiskNotFound "
                            f"at {K}+{M}, {N_OBJ}x1MiB"}

    def pcts(samples: list[float]) -> dict:
        return {"p50_ms": round(float(np.percentile(samples, 50)) * 1e3, 1),
                "p99_ms": round(float(np.percentile(samples, 99)) * 1e3, 1)}

    def run_pass(ol) -> dict:
        gets: list[float] = []
        for _ in range(GET_REPS):
            for i in range(N_OBJ):
                t0 = time.perf_counter()
                if ol.get_object_bytes("b", f"o{i}") != body:
                    raise AssertionError(f"o{i} bytes mismatch")
                gets.append(time.perf_counter() - t0)
        heals: list[float] = []
        for i in range(N_OBJ):
            t0 = time.perf_counter()
            ol.heal_object("b", f"o{i}")
            heals.append(time.perf_counter() - t0)
        return {"get": pcts(gets), "heal": pcts(heals)}

    try:
        # zero-padded dirs: rule targets match by substring, and a bare
        # ".../d1" would also hit ".../d10"-".../d19"
        disks = [XLStorage(os.path.join(root, f"d{i:02d}"))
                 for i in range(K + M)]
        ol = ErasureObjects(disks, default_parity=M)
        ol.make_bucket("b")
        for i in range(N_OBJ):
            ol.put_object("b", f"o{i}", io.BytesIO(body), OBJ)
        out["clean"] = run_pass(ol)
        def fired_count() -> float:
            return sum(v for k, v in counters_snapshot().items()
                       if "minio_tpu_hedged_reads_total" in k
                       and 'outcome="fired"' in k)

        hedged_before = fired_count()
        slow, dead = ol.disks[0], ol.disks[1]
        fault.arm(f"disk:{slow.endpoint()}:read_at:delay({DELAY_MS:.0f})")
        fault.arm(f"disk:{dead.endpoint()}:*:error(DiskNotFound)")
        out["chaos"] = run_pass(ol)
        snap = counters_snapshot()
        out["chaos"]["hedged_reads"] = {
            k.split('outcome="')[1].rstrip('"}'): v
            for k, v in snap.items()
            if "minio_tpu_hedged_reads_total" in k} or {}
        out["chaos"]["hedged_fired_during"] = fired_count() - hedged_before
        out["chaos"]["disk_states"] = {
            d.endpoint(): d.health_state() for d in ol.disks
            if hasattr(d, "health_state")
            and d.health_state() != "ok"}
        log(f"chaos 16+4 1MiB: clean get p99 "
            f"{out['clean']['get']['p99_ms']}ms -> chaos get p99 "
            f"{out['chaos']['get']['p99_ms']}ms (hedges fired: "
            f"{out['chaos']['hedged_fired_during']}); heal p99 "
            f"{out['clean']['heal']['p99_ms']} -> "
            f"{out['chaos']['heal']['p99_ms']}ms")
    finally:
        fault.clear()
        if prev_path is None:
            os.environ.pop("MINIO_TPU_GET_PATH", None)
        else:
            os.environ["MINIO_TPU_GET_PATH"] = prev_path
        if prev_cool is None:
            os.environ.pop("MINIO_TPU_HEALTH_COOLDOWN_S", None)
        else:
            os.environ["MINIO_TPU_HEALTH_COOLDOWN_S"] = prev_cool
        # let tripped-disk probe threads notice the cleared faults and
        # exit before their backing dirs vanish
        for d in (ol.disks if ol is not None else []):
            t = getattr(d, "_probe_thread", None)
            if isinstance(t, threading.Thread):
                t.join(timeout=2)
        shutil.rmtree(root, ignore_errors=True)
    return out


class _NullWriter:
    def write(self, b):
        return len(b)


def select_scan_bench(rng) -> dict:
    """Device-workloads config A (ISSUE 8 / docs/select.md): batched
    Select scan GiB/s on a numeric-predicate CSV at 1 MiB blocks x 128
    batch, against the classic per-row interpreter on a sample of the
    SAME data (the row loop runs ~MB/s, so it gets a slice and the
    ratio extrapolates — both numbers are decoded-bytes/sec)."""
    from minio_tpu.s3select import S3SelectRequest, run_select
    mb = int(os.environ.get("MINIO_TPU_BENCH_SCAN_MB", "128"))
    # ~26 B/row numeric CSV: id,v,w
    n = mb * (1 << 20) // 26
    ids = np.arange(n) % 10_000_000
    v = rng.integers(0, 1_000_000, n)
    w = rng.integers(0, 100, n)
    body = ("\n".join(f"{a},{b},{c}" for a, b, c in
                      zip(ids, v, w)) + "\n").encode()
    sql = ("SELECT _1 FROM S3Object "
           "WHERE _2 BETWEEN 990000 AND 1000000 AND _3 < 8")
    req = S3SelectRequest()
    req.expression = sql
    req.csv_header = "NONE"

    def run_with(mode: str, data: bytes) -> float:
        prev = os.environ.get("MINIO_TPU_SCAN")
        os.environ["MINIO_TPU_SCAN"] = mode
        try:
            t0 = time.perf_counter()
            run_select(req, data, _NullWriter())
            return len(data) / (time.perf_counter() - t0) / (1 << 30)
        finally:
            if prev is None:
                os.environ.pop("MINIO_TPU_SCAN", None)
            else:
                os.environ["MINIO_TPU_SCAN"] = prev

    run_with("auto", body[: 4 << 20])    # warm (jit compile)
    scan_gibs = run_with("auto", body)
    sample = body[: body.rfind(b"\n", 0, 8 << 20) + 1]
    rowloop_gibs = run_with("off", sample)
    log(f"select_scan {mb}MiB: scan {scan_gibs:.3f} GiB/s vs rowloop "
        f"{rowloop_gibs:.4f} GiB/s ({scan_gibs / rowloop_gibs:.1f}x)")
    return {"select_scan_gibs": round(scan_gibs, 3),
            "select_scan_rowloop_gibs": round(rowloop_gibs, 4),
            "select_scan_speedup": round(scan_gibs / rowloop_gibs, 1)}


def sse_put_bench(rng) -> dict:
    """Device-workloads config B (ISSUE 8 / docs/sse.md): SSE PUT
    overhead %% vs plaintext at 16+4 par8 (1 MiB bodies), per package
    cipher. AES-GCM reports null without the cryptography wheel."""
    import threading

    from minio_tpu.crypto.sse import (CIPHER_AESGCM, CIPHER_CHACHA20,
                                      HAVE_CRYPTOGRAPHY, EncryptReader,
                                      enc_size)
    from minio_tpu.objectlayer import ErasureObjects
    from minio_tpu.storage import XLStorage
    K, M, OBJ = 16, 4, 1 << 20
    N_PER = int(os.environ.get("MINIO_TPU_BENCH_SSE_NPER", "8"))
    body = rng.integers(0, 256, OBJ, dtype=np.uint8).tobytes()
    oek, iv = b"\x42" * 32, b"\x07" * 12
    root = tempfile.mkdtemp(prefix="benchsse-", dir=bench_dir())
    out: dict = {}
    try:
        disks = [XLStorage(os.path.join(root, f"d{i}"))
                 for i in range(K + M)]
        ol = ErasureObjects(disks, default_parity=M)
        ol.make_bucket("b")

        def par8(tag: str, cipher: str | None) -> float:
            def worker(j):
                for r in range(N_PER):
                    name = f"{tag}-{j}-{r}"
                    if cipher is None:
                        ol.put_object("b", name, io.BytesIO(body), OBJ)
                    else:
                        ol.put_object(
                            "b", name,
                            EncryptReader(io.BytesIO(body), oek, iv,
                                          cipher=cipher),
                            enc_size(OBJ))
            threads = [threading.Thread(target=worker, args=(j,))
                       for j in range(8)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

        par8("warm", None)
        # warm the chacha lane too (first full-package kernel compile
        # is ~20-40 s on the TPU host — must not land in the timed run)
        EncryptReader(io.BytesIO(body), oek, iv,
                      cipher=CIPHER_CHACHA20).read()
        t_plain = par8("plain", None)
        t_cha = par8("cha", CIPHER_CHACHA20)
        cha_pct = (t_cha - t_plain) / t_plain * 100
        out = {"sse_put_overhead_pct": {
            "chacha20": round(cha_pct, 1),
            "aes-gcm": None,
        }, "sse_put_plain_gibs": round(
            8 * N_PER * OBJ / t_plain / (1 << 30), 3)}
        if HAVE_CRYPTOGRAPHY:
            t_aes = par8("aes", CIPHER_AESGCM)
            out["sse_put_overhead_pct"]["aes-gcm"] = round(
                (t_aes - t_plain) / t_plain * 100, 1)
        log(f"sse_put par8 16+4: plain {t_plain:.2f}s "
            f"overhead {out['sse_put_overhead_pct']}")
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def timeline_extras() -> dict:
    """Flight-recorder artifacts for BENCH_r07+ (ISSUE 9): a truncated
    timeline of the run (newest 120 events, enough to see the last
    config's enqueue→plan→flush→complete chains per lane), the per-lane
    utilization snapshot, the standing PUT/GET/heal attribution report
    (stage p50/p99 + share of wall — the e2e configs above fed it), and
    the recorder's measured per-event cost with the derived overhead
    estimate against the encode bench.

    Overhead proof for the acceptance criterion: the encode config runs
    device-resident fori_loops that never touch the recorder, and the
    dispatch path pays <=4 recorded events per item — per-event cost ×
    4 over the ~ms-scale per-item wall is the recorder-ON tax, reported
    here so the <1% claim is a number, not an assertion."""
    from minio_tpu.obs import attribution, timeline

    # snapshot the run's timeline BEFORE the microbench floods the ring
    # with synthetic events
    artifact = {
        **timeline.status(),
        "utilization": timeline.utilization(),
        "events": timeline.snapshot(limit=120),
    }
    report = attribution.report()

    # per-event record() cost, recorder ON (default ring)
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        timeline.record("enqueue", op="bench", bytes=1 << 20)
    on_ns = (time.perf_counter() - t0) / n * 1e9
    # and the disabled early-out
    prev = os.environ.get("MINIO_TPU_TIMELINE")
    os.environ["MINIO_TPU_TIMELINE"] = "0"
    timeline.configure()
    t0 = time.perf_counter()
    for i in range(n):
        timeline.record("enqueue", op="bench", bytes=1 << 20)
    off_ns = (time.perf_counter() - t0) / n * 1e9
    if prev is None:
        os.environ.pop("MINIO_TPU_TIMELINE", None)
    else:
        os.environ["MINIO_TPU_TIMELINE"] = prev
    timeline.configure()
    # <=4 recorded events per dispatched item; a 1 MiB item at even
    # 1 GiB/s spends ~1 ms — events per item / item wall = overhead
    per_item_s = (1 << 20) / (1 << 30)
    overhead_pct = 4 * on_ns / 1e9 / per_item_s * 100
    log(f"timeline record(): {on_ns:.0f} ns/event on, {off_ns:.0f} "
        f"ns/event off -> est. {overhead_pct:.3f}% at 1 GiB/s per-item")
    return {
        "timeline": artifact,
        "attribution": report,
        "timeline_overhead": {
            "record_ns_on": round(on_ns, 1),
            "record_ns_off": round(off_ns, 1),
            "est_dispatch_overhead_pct_at_1gibs": round(overhead_pct, 4),
        },
    }


def scale_slo_extra() -> dict:
    """ISSUE 10: the mixed-workload SLO scale harness (tools/loadgen)
    as a standing bench extra for BENCH_r07+. Runs the tier-1 profile
    (1k objects, 64 mixed closed-loop clients + an open-loop arrival
    ramp, one scanner cycle forced mid-run, an admission overload
    probe) against a fresh in-process server and ships the verdict
    report minus its bulky embedded sections — the SLO verdicts,
    per-class latency/availability and the scanner attribution are the
    numbers the trajectory tracks. Scale up via MINIO_TPU_SCALE_*."""
    import tempfile

    from tools.loadgen import Profile, run_tier1_profile
    profile = Profile(
        objects=int(os.environ.get("MINIO_TPU_SCALE_OBJECTS", "1000")),
        clients=int(os.environ.get("MINIO_TPU_SCALE_CLIENTS", "64")),
        duration_s=float(os.environ.get("MINIO_TPU_SCALE_DURATION",
                                        "6")),
        open_rps=float(os.environ.get("MINIO_TPU_SCALE_OPEN_RPS",
                                      "50")),
        # multi-tenant spread (ISSUE 18): 48 buckets against the
        # default top_n=32 registry forces real folding, so the
        # bucket_metrics_bounded_ok verdict gates on a scrape that
        # actually had to bound itself
        buckets=int(os.environ.get("MINIO_TPU_SCALE_BUCKETS", "48")),
    )
    with tempfile.TemporaryDirectory(prefix="bench-slo-") as root:
        rep = run_tier1_profile(root, profile)
    slim = {k: v for k, v in rep.items()
            if k not in ("health", "slo", "per_op")}
    slim["slo_interactive_5m"] = \
        rep["slo"]["classes"]["interactive"]["windows"]["5m"]
    slim["slo_breach"] = {
        cls: ent["breach"] for cls, ent in rep["slo"]["classes"].items()}
    log(f"scale_slo: {rep['requests_total']} reqs @ {rep['rps']}/s, "
        f"passed={rep['verdicts']['passed']}")
    # degraded-GET + heal interactive mix (ISSUE 13): a second, smaller
    # run with one disk's shard reads killed — GETs reconstruct on the
    # interactive device lane, a heal worker rebuilds concurrently, and
    # the interactive class's own burn rates judge the latency tier.
    # MINIO_TPU_SCALE_DEGRADED=0 skips.
    if os.environ.get("MINIO_TPU_SCALE_DEGRADED", "1") != "0":
        dprofile = Profile(
            objects=int(os.environ.get(
                "MINIO_TPU_SCALE_DEGRADED_OBJECTS", "128")),
            clients=int(os.environ.get(
                "MINIO_TPU_SCALE_DEGRADED_CLIENTS", "16")),
            duration_s=float(os.environ.get(
                "MINIO_TPU_SCALE_DEGRADED_DURATION", "4")),
            value_bytes=256 << 10,   # above the 128 KiB inline line
            open_rps=0.0,
            degraded=True,
        )
        with tempfile.TemporaryDirectory(prefix="bench-slo-deg-") as root:
            drep = run_tier1_profile(root, dprofile)
        slim["degraded"] = {
            "profile": drep["profile"],
            "degraded": drep["degraded"],
            "interactive": drep["per_class"].get("interactive", {}),
            "verdicts": {k: v for k, v in drep["verdicts"].items()
                         if k.startswith("degraded") or k == "passed"},
        }
        log(f"scale_slo degraded: reconstruct items="
            f"{drep['degraded'].get('interactive_lane_items')} heals="
            f"{drep['degraded'].get('heals')} passed="
            f"{drep['verdicts']['passed']}")
    # replication-chaos phase (ISSUE 19): a third run on a real 4-node
    # topology with a replication rule at node 3, the target killed
    # mid-stream and rejoined — the no_replica_obligation_lost /
    # replication_backlog_drained / replication_lag_slo_ok verdicts
    # gate it. MINIO_TPU_SCALE_REPLICATION=0 skips.
    if os.environ.get("MINIO_TPU_SCALE_REPLICATION", "1") != "0":
        from tools.loadgen import run_topology_profile
        rprofile = Profile(
            objects=int(os.environ.get(
                "MINIO_TPU_SCALE_REPLICATION_OBJECTS", "128")),
            clients=int(os.environ.get(
                "MINIO_TPU_SCALE_REPLICATION_CLIENTS", "8")),
            duration_s=float(os.environ.get(
                "MINIO_TPU_SCALE_REPLICATION_DURATION", "6")),
            open_rps=0.0,
            scanner_mid_run=False,
            overload_probe=False,
            notifier_probe=False,
            replication_target_node=3,
        )
        with tempfile.TemporaryDirectory(prefix="bench-slo-rep-") \
                as root:
            rrep = run_topology_profile(root, rprofile, nodes=4,
                                        disks_per_node=2)
        rsec = dict(rrep["replication"])
        rsec.pop("lost_replicas", None)
        slim["replication"] = {
            "profile": rrep["profile"],
            "replication": rsec,
            "verdicts": {k: v for k, v in rrep["verdicts"].items()
                         if "replica" in k or "replication" in k or
                         k == "passed"},
        }
        log(f"scale_slo replication: acked="
            f"{rsec.get('acked_writes')} lost="
            f"{rsec.get('lost_count')} drain="
            f"{rsec.get('drain_s')}s passed="
            f"{rrep['verdicts']['passed']}")
    return {"scale_slo": slim}


def node_chaos_extra() -> dict:
    """ISSUE 12: clean vs kill-1-of-4 on a real 4-node topology
    (dist.harness.LocalCluster — separate listeners, storage REST,
    dsync locks). Reports S3 PUT/GET p50/p99 with all nodes up, the
    same with one node killed mid-bench (write-quorum degraded writes +
    cross-peer reads), and the heal-drain seconds after the node
    rejoins — the BENCH_r07+ trajectory for the node fault-tolerance
    plane. MINIO_TPU_NODE_CHAOS_BENCH=0 skips."""
    if os.environ.get("MINIO_TPU_NODE_CHAOS_BENCH", "1") == "0":
        return {}
    import tempfile
    import time as _t

    from minio_tpu.dist.harness import LocalCluster
    from tools.loadgen import _SigClient

    ops = int(os.environ.get("MINIO_TPU_NODE_CHAOS_OPS", "12"))
    body = np.random.default_rng(5).integers(
        0, 256, 256 << 10, dtype=np.uint8).tobytes()

    def pcts(vals):
        vs = sorted(vals)
        return {"p50_ms": round(vs[len(vs) // 2] * 1e3, 1),
                "p99_ms": round(vs[min(len(vs) - 1,
                                       int(0.99 * len(vs)))] * 1e3, 1)}

    def measure(cl, tag):
        puts, gets = [], []
        for i in range(ops):
            t0 = _t.perf_counter()
            r = cl.request("PUT", f"/ncb/{tag}{i:03d}", body=body)
            assert r.status_code == 200, (tag, i, r.status_code)
            puts.append(_t.perf_counter() - t0)
            t0 = _t.perf_counter()
            r = cl.request("GET", f"/ncb/{tag}{i:03d}")
            assert r.status_code == 200 and len(r.content) == len(body)
            gets.append(_t.perf_counter() - t0)
        return {"put": pcts(puts), "get": pcts(gets)}

    def repl_leg(lc, cl, tag, target_idx, kill):
        """One replication leg (ISSUE 19 trajectory): rule at
        ``target_idx``, ``ops`` unique PUTs (with a mid-stream
        kill/restart of the target when ``kill``), then the backlog
        drained to zero and the per-leg lag quantiles read off a
        fresh lag window."""
        from minio_tpu.obs.latency import Window
        src, dstb = f"rsrc-{tag}", f"rdst-{tag}"
        cl.request("PUT", f"/{src}")
        xml = (
            "<ReplicationConfiguration><Rule><ID>bench</ID>"
            "<Status>Enabled</Status><Priority>1</Priority>"
            "<Destination>"
            f"<Bucket>{dstb}</Bucket><Endpoint>{lc.urls[target_idx]}"
            "</Endpoint></Destination></Rule>"
            "</ReplicationConfiguration>").encode()
        r = cl.request("PUT", f"/{src}", query={"replication": ""},
                       body=xml)
        assert r.status_code == 200, r.status_code
        rs = lc.nodes[0].server.replication_sys
        rs.lag = Window()        # per-leg quantiles, not cumulative
        for i in range(ops):
            if kill and i == ops // 3:
                lc.kill(target_idx)
            if kill and i == 2 * ops // 3:
                lc.restart(target_idx)
            r = cl.request("PUT", f"/{src}/o{i:03d}", body=body)
            assert r.status_code == 200, (tag, i, r.status_code)
        t0 = _t.monotonic()
        drained = False
        while _t.monotonic() - t0 < 120:
            st = rs.stats()
            if st["queued"] + st["retry_pending"] == 0:
                drained = True
                break
            _t.sleep(0.1)
        lagr = rs.lag_report()
        return src, {
            "lag_p50_ms": round(lagr["lag_p50_s"] * 1e3, 1),
            "lag_p99_ms": round(lagr["lag_p99_s"] * 1e3, 1),
            "drain_s": round(_t.monotonic() - t0, 2),
            "drained": drained,
            "backlog": lagr["backlog"],
        }

    with tempfile.TemporaryDirectory(prefix="bench-nc-") as root:
        lc = LocalCluster(root, nodes=4, disks_per_node=2, parity=2)
        try:
            cl = _SigClient(lc.endpoint(0), lc.access_key,
                            lc.secret_key)
            r = cl.request("PUT", "/ncb")
            assert r.status_code == 200, r.status_code
            clean = measure(cl, "c")
            lc.kill(3)
            degraded = measure(cl, "k")
            lc.restart(3)
            t0 = _t.monotonic()
            drained = False
            while _t.monotonic() - t0 < 120:
                mrf = getattr(lc.nodes[0].server, "mrf", None)
                if mrf is not None and mrf.stats()["queued"] == 0:
                    drained = True
                    break
                _t.sleep(0.25)
            drain_s = round(_t.monotonic() - t0, 2)
            # replication trajectory (ISSUE 19): lag quantiles + drain
            # seconds with the target healthy vs killed-and-rejoined
            # mid-stream, plus a forced full-bucket resync replay
            rs = getattr(lc.nodes[0].server, "replication_sys", None)
            replication: dict = {}
            if rs is not None:
                _, replication["clean"] = repl_leg(lc, cl, "cl", 1,
                                                   kill=False)
                ksrc, replication["kill_target"] = repl_leg(
                    lc, cl, "kt", 3, kill=True)
                t0 = _t.monotonic()
                n_resync = rs.resync(ksrc, force=True)
                while _t.monotonic() - t0 < 120:
                    st = rs.stats()
                    if st["queued"] + st["retry_pending"] == 0:
                        break
                    _t.sleep(0.1)
                replication["resync"] = {
                    "drain_s": round(_t.monotonic() - t0, 2),
                    "resynced": n_resync,
                }
        finally:
            lc.shutdown()
    out = {"clean": clean, "kill_1_of_4": degraded,
           "heal_drain_s": drain_s, "heal_drained": drained,
           "replication": replication}
    log(f"node_chaos: clean put p99 {clean['put']['p99_ms']}ms vs "
        f"kill-1-of-4 {degraded['put']['p99_ms']}ms, heal drain "
        f"{drain_s}s")
    if replication:
        log(f"node_chaos replication: clean lag p99 "
            f"{replication['clean']['lag_p99_ms']}ms vs kill-target "
            f"{replication['kill_target']['lag_p99_ms']}ms, resync "
            f"drain {replication['resync']['drain_s']}s")
    return {"node_chaos": out}


def finish(payload: dict) -> None:
    """Print the one-line result, quiesce framework threads, and exit 0
    deterministically. The axon JAX client's teardown intermittently aborts
    the process (pthread-cancel of a C++ thread -> "FATAL: exception not
    rethrown") after all useful work is done; our own threads are stopped
    first, output is flushed, then os._exit skips the crash-prone
    interpreter/third-party finalization."""
    print(json.dumps(payload))
    sys.stdout.flush()
    sys.stderr.flush()
    import minio_tpu
    minio_tpu.shutdown()
    os._exit(0)


def device_obs_extra() -> dict:
    """Device-plane observability snapshot (ISSUE 16): HBM ledger
    high-water marks, the compile table totals, and per-op roofline
    ratios accumulated across EVERY config above — the bench's own
    device traffic doubles as the evidence run. Slimmed to the leaves
    bench_compare knows how to judge (roofline up-better, compile
    seconds down-better, ledger counts non-headline)."""
    from minio_tpu.obs import device
    st = device.status(touch_backend=True)
    ledger = {lane: {"peak_bytes": row["peak_bytes"],
                     "peak_buffers": row["peak_buffers"],
                     "acquired_total": row["acquired_total"],
                     "donated_total": row["donated_total"]}
              for lane, row in st["ledger"].items()}
    comp = st["compile"]
    roofline = {op: {"roofline_ratio": row["roofline_ratio"],
                     "achieved_gibs": row["achieved_gibs"],
                     "device_seconds": round(row["device_seconds"], 4),
                     "flushes": row["flushes"]}
                for op, row in st["roofline"].items()}
    return {"device_obs": {
        "ledger": ledger,
        "ledger_balanced": st["ledger_balanced"],
        "compiles_total": comp["compiles_total"],
        "compile_seconds_total": round(comp["compile_seconds_total"], 3),
        "compile_storms_total": comp["storms_total"],
        "roofline": roofline,
    }}


def bucket_stats_extra() -> dict:
    """Per-bucket analytics scrape cost (ISSUE 18): the registry folds
    past ``top_n`` buckets, so a 4096-bucket storm must render in about
    the same wall time (and the same series count) as 16 buckets — the
    acceptance bound is scrape_4096 <= 2x scrape_16. Driven directly
    against the registry (the s3api charge path is one dict update on
    top of this), then reset so the synthetic storm leaves no trace in
    later extras."""
    import time as _t

    from minio_tpu.obs import bucketstats as bstats

    def drive(n: int) -> tuple[float, int, dict]:
        bstats.reset()
        for i in range(n):
            bstats.record_request(
                f"bench-{i:05d}", "getobject", 200, 0.002,
                ttfb_s=0.0005, bytes_in=128, bytes_out=4096)
        best = float("inf")
        for _ in range(5):
            t0 = _t.perf_counter()
            lines = bstats.metric_lines()
            best = min(best, (_t.perf_counter() - t0) * 1e3)
        labels = {ln.split('bucket="', 1)[1].split('"', 1)[0]
                  for ln in lines if 'bucket="' in ln}
        rep = bstats.report()
        return best, len(labels), rep

    ms16, labels16, _ = drive(16)
    ms4096, labels4096, rep = drive(4096)
    bstats.reset()
    out = {
        "scrape_16_ms": round(ms16, 3),
        "scrape_4096_ms": round(ms4096, 3),
        "scrape_scaling_overhead": round(ms4096 / max(ms16, 1e-9), 2),
        "series_labels": labels4096,
        "tracked": rep["tracked"],
        "fold_hits": rep["folds"],
    }
    log(f"bucket_stats: scrape 16={out['scrape_16_ms']}ms "
        f"4096={out['scrape_4096_ms']}ms "
        f"(x{out['scrape_scaling_overhead']}), "
        f"labels {labels16}->{labels4096}, folds {rep['folds']}")
    return {"bucket_stats": out}


def main() -> None:
    chaos = "--chaos" in sys.argv[1:]
    rng = np.random.default_rng(0)
    cpu_gibs = cpu_baseline(rng)
    host = host_profile(rng)
    # e2e before the device configs: the device stages' multi-GiB host
    # staging churn measurably degrades kernel page allocation afterwards
    # (tmpfs writes -25%, syscall time ~2x on this host), which would tax
    # the e2e numbers with state the data plane didn't create
    put = e2e_put(rng)
    # durability tax rides the disk-bound slot too
    fsy = fsync_put(rng)
    # chaos rides the same disk-bound slot (before device staging churn)
    cha = chaos_profile(rng) if chaos else None
    dev = device_configs(rng)
    lat = heal_latency(rng)
    # interactive device lane (ISSUE 13): heal-shard p50/p99 on both
    # lane disciplines — rides the same global queue as heal_latency
    ia_lane = interactive_lane_extra(rng)
    # device workloads (ISSUE 8): Select scan + SSE package crypto
    scan = select_scan_bench(rng)
    sse = sse_put_bench(rng)
    # mixed-workload SLO scale harness (ISSUE 10) — after the kernel
    # configs, before the timeline snapshot so its traffic shows there
    scale = scale_slo_extra()
    # node fault tolerance on the 4-node topology (ISSUE 12)
    node_chaos = node_chaos_extra()
    # flight-recorder artifacts LAST so the truncated timeline +
    # attribution report cover every config above (ISSUE 9)
    tl = timeline_extras()
    # device-plane ledger/compile/roofline accumulated over the whole
    # run — snapshot after every config has dispatched (ISSUE 16)
    dev_obs = device_obs_extra()
    # per-bucket analytics scrape cost, AFTER the loadgen extras so the
    # synthetic 4096-bucket storm can reset the registry freely (ISSUE 18)
    bucket_stats = bucket_stats_extra()

    enc = dev["encode_16p4_1MiB_b128"]
    extra_chaos = {"chaos": cha} if cha is not None else {}
    # host-CPU attribution windows (ISSUE 14): one per bounded config,
    # assembled as the standing `host_profile` extra
    host_profile = {"put_par8_16p4": put.pop("host_profile", {}),
                    "heal": lat.pop("host_profile", {})}
    finish({
        "metric": "erasure_encode_gibs_16+4_1MiB_batch128",
        "value": round(enc, 2),
        "unit": "GiB/s",
        "vs_baseline": round(enc / cpu_gibs, 2),
        "extra": {
            "cpu_avx2_encode_gibs": round(cpu_gibs, 2),
            "host": host,
            "host_profile": host_profile,   # ISSUE 14 evidence channel
            "e2e_put_gibs": put,                      # config 1
            "fsync_put_gibs": fsy,             # durability tax (PR 6)
            "encode_sweep_8p4_gibs": dev["encode_sweep_8p4"],  # config 2
            "reconstruct_2loss_gibs": round(
                dev["reconstruct_2loss_16p4_b128"], 2),        # config 3
            "fused_verify_reconstruct_gibs": round(
                dev["fused_verify_reconstruct_16p4_b128"], 2),  # config 4
            "fused_verify_reconstruct_hh_gibs": round(
                dev["fused_verify_reconstruct_16p4_b128_hh"], 2),
            "batched_heal_rebuild_gibs": round(
                dev["batched_heal_rebuild_b128"], 2),           # config 5
            "heal_shard_latency": lat,                # north-star p99 half
            **ia_lane,     # both-lanes heal p50/p99 (ISSUE 13)
            "reconstruct_vs_cpu": round(
                dev["reconstruct_2loss_16p4_b128"] / cpu_gibs, 2),
            **scan,                  # device workloads A (docs/select.md)
            **sse,                   # device workloads B (docs/sse.md)
            **scale,      # mixed-workload SLO scale harness (ISSUE 10)
            **node_chaos,      # 4-node kill/heal topology (ISSUE 12)
            **tl,     # flight-recorder timeline + attribution (ISSUE 9)
            **dev_obs,   # HBM ledger + compile + roofline (ISSUE 16)
            **bucket_stats,  # bounded per-bucket scrape cost (ISSUE 18)
            **extra_chaos,                        # --chaos degraded run
        },
    })


if __name__ == "__main__":
    main()
